"""Structured event bus: append-only JSONL streams, one per rank.

The unit of telemetry is an *event*: one JSON object per line, stamped
with everything needed to reconstruct a multi-process run after the
fact — schema version, emitting rank + pid, a per-process sequence
number, and BOTH clocks:

- ``mono`` (``time.monotonic()``) orders events. CLOCK_MONOTONIC is
  shared by every process on one host, which is exactly the supervised
  dryrun's topology (supervisor + ranks on one machine) — the same
  clock-discipline argument as ``resilience.heartbeat``. Wall clocks
  jump (NTP slew/step); an event log ordered by wall time can show a
  restart *before* the failure that caused it.
- ``wall`` (``time.time()``) is carried as a human-readable timestamp
  field only, never as an ordering key.

Writers append + flush one line per event, so the only torn state a
crash can leave is a truncated LAST line — which :func:`read_events`
tolerates by skipping undecodable lines instead of failing the whole
post-mortem (the log exists precisely for runs that died mid-write).
Opt-in ``durable=True`` additionally fsyncs each emit so the line also
survives power loss/kernel death; it stays off by default because an
fsync per event is a disk round trip where a flush is ~microseconds,
and the process-crash case the bus is built for does not need it.

A relaunched rank (same rank id, new pid, new attempt) appends to the
same per-rank file: one stream per rank across the run's whole
supervised lifetime, with ``pid``/``seq`` telling attempts apart.
"""
from __future__ import annotations

import glob
import json
import os
import threading
import time
from typing import IO, Any, Callable, Iterable

SCHEMA_VERSION = 1

# stamp fields the bus owns; emit() refuses payload keys that would
# silently shadow them
RESERVED_FIELDS = ("v", "kind", "rank", "pid", "seq", "mono", "wall")


def stream_path(directory: str, name: str) -> str:
    return os.path.join(directory, f"events.{name}.jsonl")


class EventBus:
    """One process's writer end of the event stream.

    >>> bus = EventBus(obs_dir, rank=0)
    >>> bus.emit("run_start", config="ppo-mlp-synth64", iterations=100)
    >>> bus.close()

    ``name`` sets the stream file (``events.<name>.jsonl``); it defaults
    to ``rank<r>`` so per-rank streams sort naturally. Non-rank emitters
    (the supervisor) pass ``rank=-1`` and a readable name. ``clock`` /
    ``wall`` are injectable for deterministic ordering tests.
    """

    def __init__(self, directory: str, rank: int = 0,
                 name: str | None = None,
                 clock: Callable[[], float] = time.monotonic,
                 wall: Callable[[], float] = time.time,
                 durable: bool = False):
        os.makedirs(directory, exist_ok=True)
        self.directory = directory
        self.rank = int(rank)
        self.name = name if name is not None else f"rank{self.rank}"
        self.path = stream_path(directory, self.name)
        self._clock = clock
        self._wall = wall
        self._seq = 0
        # durable=True fsyncs every emit: the line survives power loss,
        # not just process death. Default stays flush-only — a flush
        # reaches the OS page cache (enough for the crash post-mortems
        # this bus exists for, where the kernel outlives the process)
        # at ~microseconds per event, while fsync costs a disk round
        # trip per event and belongs only on streams that feed durable
        # ledgers (the flywheel's promotion lineage, kill-mid-write
        # tests)
        self.durable = bool(durable)
        # the async engine's actor thread and the learner (caller)
        # thread share one rank's bus: serialize the stamp+write so seq
        # stays gapless and lines never interleave mid-record
        self._emit_lock = threading.Lock()
        self._file: IO[str] | None = open(self.path, "a")

    def emit(self, kind: str, **fields: Any) -> dict:
        """Append one event; returns the full stamped record. Payload
        values must be JSON-serializable (the writer fails loudly at the
        emit site rather than leaving a poisoned line)."""
        if self._file is None:
            raise ValueError(f"event bus {self.path} is closed")
        bad = [k for k in fields if k in RESERVED_FIELDS]
        if bad:
            raise ValueError(f"event field(s) {bad} shadow the bus's own "
                             f"stamp fields {RESERVED_FIELDS}")
        with self._emit_lock:
            event = {"v": SCHEMA_VERSION, "kind": kind, "rank": self.rank,
                     "pid": os.getpid(), "seq": self._seq,
                     "mono": self._clock(), "wall": self._wall(), **fields}
            self._seq += 1
            self._file.write(json.dumps(event, sort_keys=True) + "\n")
            self._file.flush()
            if self.durable:
                os.fsync(self._file.fileno())
        return event

    def close(self) -> None:
        if self._file is not None:
            self._file.close()
            self._file = None

    def __enter__(self) -> "EventBus":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def read_events(path: str) -> list[dict]:
    """Read one stream, tolerating a torn/truncated last line (the one
    state a crashed writer can leave — each event is a single buffered
    write + flush). Undecodable or non-object lines are skipped, not
    fatal: the reader exists for post-mortems of runs that died
    mid-write."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                event = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(event, dict):
                out.append(event)
    return out


def event_streams(directory: str) -> list[str]:
    """Every stream file under ``directory``, sorted for determinism."""
    return sorted(glob.glob(stream_path(directory, "*")))


def merge_events(events: Iterable[dict]) -> list[dict]:
    """Order interleaved per-rank events into ONE timeline: primary key
    is the shared monotonic clock; ``(rank, seq)`` breaks exact ties
    deterministically (seq alone also fixes the order of same-process
    events, whose mono stamps are already strictly increasing)."""
    return sorted(events,
                  key=lambda e: (e.get("mono", e.get("wall", 0.0)),
                                 e.get("rank", 0), e.get("seq", 0)))


def merge_dir(directory: str) -> list[dict]:
    """Merge every per-rank stream under ``directory`` into one ordered
    timeline. Raises FileNotFoundError when the directory holds no
    streams at all (an empty post-mortem should fail loudly)."""
    paths = event_streams(directory)
    if not paths:
        raise FileNotFoundError(
            f"no event streams (events.*.jsonl) under {directory}")
    merged: list[dict] = []
    for p in paths:
        merged.extend(read_events(p))
    return merge_events(merged)
