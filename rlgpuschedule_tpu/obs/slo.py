"""Declarative SLOs evaluated as multi-window burn rates (ISSUE 20).

``slo_snapshot()`` answered "what are the percentiles right now" and
only when someone remembered to call it. This module makes "are we
meeting the SLO" a first-class, alarm-wired answer: an
:class:`SLOSpec` declares an objective (a target success fraction —
availability = 1 − shed/fail rate, or a latency target expressed as the
fraction of requests under a bound), and the :class:`SLOEngine`
evaluates it continuously as **burn rates** over several sliding
windows of a cumulative ``(bad, total)`` event stream.

Burn rate is the SRE workbook quantity: the windowed error rate divided
by the error budget (``1 − objective``). Burn 1.0 spends exactly the
budget over the window; burn 14 torches it. Evaluating the same SLI
over a short AND a long window makes the alert both fast-firing and
fast-clearing: the alert condition requires **every** window of the
spec to exceed its threshold, so a transient spike trips it quickly
(all windows saturate together) and the short window un-trips it
quickly once the bleeding stops.

Surfaces, all refreshed by a :meth:`Registry.collect` pre-scrape
collector hook (never stale — registration wires the engine into every
``render()``):

- ``slo_burn_rate{slo=...,window=...}`` — per-window burn gauges;
- ``slo_error_budget_remaining{slo=...}`` — rolling error budget over
  the spec's budget window, in [0, 1]; it RECOVERS as the window
  slides past an incident (this is deliberately not the calendar-
  period budget: a serving rig wants "are we still bleeding", not
  "how was the quarter");
- ``slo_burn_alerts_total{slo=...}`` — alert edge counter;
- bus events ``slo_burn_alert`` (rising edge, carries the per-window
  burns) and ``slo_burn_clear`` (falling edge, carries the recovered
  budget) — neither is an alarm kind, so ``--strict-alarms`` stays a
  compile/transfer contract while SLO health gets its own channel.

The engine never reads metrics by name: each spec is registered with a
``sample()`` callable returning the cumulative ``(bad, total)`` pair,
so any counter arithmetic (shed + dispatch errors + retry hedges) or
histogram tail (:func:`histogram_sli`) can be an SLI without the
engine knowing the serving layer's metric names.
"""
from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Callable

from .metrics import Histogram, Registry

# the default multi-window ladder (scaled-down SRE workbook shape):
# (window_seconds, burn threshold) — every window must exceed its
# threshold for the spec to alert
DEFAULT_WINDOWS = ((60.0, 14.4), (300.0, 6.0), (3600.0, 1.0))


@dataclasses.dataclass(frozen=True)
class SLOSpec:
    """One declarative objective.

    ``objective`` is the target success fraction (0.999 availability =
    "at most 1 in 1000 requests shed or failed"); for a latency SLO the
    *SLI itself* encodes the latency target (bad = requests over the
    bound) and ``objective`` is the fraction required under it.
    ``windows`` is the multi-window burn ladder; ``budget_window_s``
    (default: the longest window) is the sliding window the
    error-budget gauge is computed over.
    """

    name: str
    objective: float
    windows: "tuple[tuple[float, float], ...]" = DEFAULT_WINDOWS
    budget_window_s: "float | None" = None
    description: str = ""

    def __post_init__(self):
        if not 0.0 < self.objective < 1.0:
            raise ValueError(f"slo {self.name!r}: objective must be in "
                             f"(0, 1), got {self.objective}")
        if not self.windows:
            raise ValueError(f"slo {self.name!r}: need >= 1 window")
        for w, thresh in self.windows:
            if w <= 0 or thresh <= 0:
                raise ValueError(f"slo {self.name!r}: bad window "
                                 f"({w}, {thresh})")
        if self.budget_window_s is not None and self.budget_window_s <= 0:
            raise ValueError(f"slo {self.name!r}: budget_window_s must "
                             f"be positive")

    @property
    def budget_window(self) -> float:
        if self.budget_window_s is not None:
            return self.budget_window_s
        return max(w for w, _ in self.windows)


def histogram_sli(hist: Histogram, target_s: float) -> Callable:
    """SLI over a fixed-bucket :class:`Histogram`: bad = observations
    in buckets strictly above the largest bucket bound <= ``target_s``
    (conservative — a target between bounds counts the straddling
    bucket as bad), total = all observations."""
    bounds = [le for le in hist.buckets if le <= float(target_s)]
    if not bounds:
        raise ValueError(f"latency target {target_s}s is below the "
                         f"lowest bucket bound {hist.buckets[0]}s")
    le = bounds[-1]

    def sample() -> "tuple[float, float]":
        good = 0
        for b, acc in hist.cumulative():
            if b == le:
                good = acc
                break
        return float(hist.count - good), float(hist.count)

    return sample


class _Watch:
    __slots__ = ("spec", "sample", "samples", "alerting",
                 "g_burn", "g_budget", "c_alerts")

    def __init__(self, spec, sample, registry):
        self.spec = spec
        self.sample = sample
        # (t, bad, total) cumulative samples, pruned past the horizon
        self.samples: deque = deque()
        self.alerting = False
        self.g_burn = {
            w: registry.gauge(
                "slo_burn_rate",
                "windowed error rate over the error budget, per SLO "
                "window (1.0 = spending exactly the budget)",
                labels={"slo": spec.name, "window": f"{w:g}s"})
            for w, _ in spec.windows}
        self.g_budget = registry.gauge(
            "slo_error_budget_remaining",
            "rolling error budget left over the SLO's budget window, "
            "in [0, 1] (recovers as the window slides past an incident)",
            labels={"slo": spec.name})
        self.c_alerts = registry.counter(
            "slo_burn_alerts_total",
            "burn-rate alert rising edges per SLO",
            labels={"slo": spec.name})


class SLOEngine:
    """Evaluates registered :class:`SLOSpec` s on every ``collect()``.

    Construction registers the engine as a pre-scrape collector on the
    registry, so every ``render()`` (file snapshot, HTTP scrape) gets
    freshly computed burn/budget gauges; ``close()`` deregisters it.
    ``clock`` is injectable for deterministic tests.
    """

    def __init__(self, registry: Registry, bus=None, clock=None):
        self._registry = registry
        self._bus = bus
        self._clock = clock if clock is not None else time.monotonic
        self._watches: "list[_Watch]" = []
        registry.add_collector(self.collect)

    def watch(self, spec: SLOSpec, sample: Callable) -> SLOSpec:
        """Register ``spec`` over ``sample() -> (bad, total)`` (both
        cumulative, monotone non-decreasing). Returns the spec for
        chaining."""
        if any(w.spec.name == spec.name for w in self._watches):
            raise ValueError(f"slo {spec.name!r} already watched")
        self._watches.append(_Watch(spec, sample, self._registry))
        return spec

    def _delta(self, watch: _Watch, now: float,
               window: float) -> "tuple[float, float]":
        """(bad, total) accumulated over the trailing ``window``:
        current sample minus the newest sample at or before the window
        start (the oldest retained sample when history is shorter)."""
        t, bad, total = watch.samples[-1]
        base = watch.samples[0]
        for s in watch.samples:
            if s[0] <= now - window:
                base = s
            else:
                break
        return bad - base[1], total - base[2]

    def collect(self) -> None:
        now = self._clock()
        for watch in self._watches:
            spec = watch.spec
            bad, total = watch.sample()
            watch.samples.append((now, float(bad), float(total)))
            horizon = max(spec.budget_window,
                          max(w for w, _ in spec.windows))
            while len(watch.samples) > 2 \
                    and watch.samples[1][0] <= now - horizon:
                watch.samples.popleft()
            budget_frac = 1.0 - spec.objective
            burns = {}
            alerting = True
            for w, thresh in spec.windows:
                db, dt = self._delta(watch, now, w)
                err = (db / dt) if dt > 0 else 0.0
                burn = err / budget_frac
                burns[w] = burn
                watch.g_burn[w].set(burn)
                if not (dt > 0 and burn >= thresh):
                    alerting = False
            db, dt = self._delta(watch, now, spec.budget_window)
            spent = (db / (dt * budget_frac)) if dt > 0 else 0.0
            budget = min(1.0, max(0.0, 1.0 - spent))
            watch.g_budget.set(budget)
            if alerting and not watch.alerting:
                watch.c_alerts.inc()
                if self._bus is not None:
                    self._bus.emit(
                        "slo_burn_alert", slo=spec.name,
                        objective=spec.objective,
                        burns={f"{w:g}s": round(b, 3)
                               for w, b in burns.items()},
                        budget_remaining=budget)
            elif watch.alerting and not alerting:
                if self._bus is not None:
                    self._bus.emit("slo_burn_clear", slo=spec.name,
                                   budget_remaining=budget)
            watch.alerting = alerting

    def status(self) -> "dict[str, dict]":
        """Point-in-time view per spec (after the last collect)."""
        out = {}
        for watch in self._watches:
            out[watch.spec.name] = {
                "alerting": watch.alerting,
                "budget_remaining": watch.g_budget.value,
                "budget_window_s": watch.spec.budget_window,
                "burn": {f"{w:g}s": g.value
                         for w, g in watch.g_burn.items()},
                "alerts_total": watch.c_alerts.value,
            }
        return out

    def close(self) -> None:
        self._registry.remove_collector(self.collect)
