"""Run-loop telemetry: iteration spans + production alarms.

:class:`RunTelemetry` is what the train loops hold — one object owning
the event bus (:mod:`.events`), the counters/gauges registry
(:mod:`.metrics`), the host-side phase timer
(``utils.profiling.SectionTimer``) and, opt-in, the :class:`Alarms`.

Host-sync discipline (the whole design constraint): telemetry never
touches device values. Phase timings are host clocks; the ``iteration``
event is emitted only at logged iterations, carrying the metrics dict
the run loop ALREADY materialized through its single batched
``device_get`` — so an instrumented run performs exactly the same
host↔device syncs as a bare one (asserted in tests/test_obs.py).

:class:`Alarms` promotes PR 3's test-only sentinels to production:

- **recompile** — a ``CompileCounter`` (jax.monitoring listeners) spans
  the run; any trace/compile activity observed during a post-warmup
  dispatch emits a ``recompile`` event and bumps a counter instead of
  only failing a sanitize test. Legitimate re-traces (warmup, the
  watchdog's LR-rescale rollback) are granted amnesty via
  :meth:`Alarms.expect_recompile` and land as ``compile`` events.
- **transfer** — post-warmup dispatches run under
  ``jax.transfer_guard("disallow")``: an implicit host↔device transfer
  in the hot path emits a ``transfer`` event and raises
  :class:`AlarmError` (fail fast WITH telemetry — the buffer-donating
  dispatch cannot be safely retried after a mid-trace abort).
- **slow_iteration** — optionally, an iteration whose wall time exceeds
  ``slow_iter_s`` emits the event and arms a one-shot ``jax.profiler``
  trace capture of the NEXT iteration (profiling the slow iteration
  itself is impossible — it already happened).
"""
from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Any, Callable, Iterator, Mapping

from ..analysis.sentinels import CompileCounter, no_implicit_transfers
from ..utils.profiling import SectionTimer
from .events import EventBus
from .metrics import Registry
from .trace import Tracer

PROM_SNAPSHOT = "metrics.prom"


class AlarmError(RuntimeError):
    """A production alarm that cannot be survived in place (an implicit
    transfer inside a buffer-donating dispatch)."""


class Alarms:
    """Production alarm scope. Use as a context manager spanning the run;
    wrap each jitted dispatch in :meth:`dispatch`.

    ``warmup_iters`` dispatches are exempt (the first iteration MUST
    compile); compile activity inside them is still recorded, as
    ``compile`` events, so the post-mortem shows where compile time
    went. ``expect_recompile(reason)`` grants the next dispatch the same
    amnesty — the run loop calls it after a watchdog rollback, whose LR
    rescale legitimately re-traces the step.
    """

    def __init__(self, bus: EventBus, registry: Registry | None = None,
                 warmup_iters: int = 1, transfer_guard: bool = True,
                 slow_iter_s: float | None = None,
                 profile_dir: str | None = None):
        if warmup_iters < 0:
            raise ValueError(f"warmup_iters must be >= 0, got "
                             f"{warmup_iters}")
        self.bus = bus
        self.registry = registry if registry is not None else Registry()
        self.warmup_iters = warmup_iters
        self.transfer_guard = transfer_guard
        self.slow_iter_s = slow_iter_s
        self.profile_dir = profile_dir
        self._counter: CompileCounter | None = None
        self._dispatches = 0
        self._amnesty: str | None = None
        self._profile_pending = False
        self._profile_active = False
        self._profile_done = False
        self._recompiles = self.registry.counter(
            "rlsched_recompile_alarms_total",
            "post-warmup dispatches that traced or compiled")
        self._transfers = self.registry.counter(
            "rlsched_transfer_alarms_total",
            "implicit host-device transfers caught in the hot path")
        self._slow = self.registry.counter(
            "rlsched_slow_iteration_alarms_total",
            "iterations slower than the slow_iter_s threshold")

    def __enter__(self) -> "Alarms":
        self._counter = CompileCounter().__enter__()
        return self

    def __exit__(self, *exc) -> None:
        self.stop_profile()
        if self._counter is not None:
            self._counter.__exit__(*exc)
            self._counter = None

    def expect_recompile(self, reason: str) -> None:
        """Grant the NEXT dispatch compile amnesty (e.g. a rollback's LR
        rescale rebinds the optimizer and re-traces legitimately)."""
        self._amnesty = reason

    @contextlib.contextmanager
    def dispatch(self, iteration: int) -> Iterator[None]:
        """Wrap one jitted dispatch: count compile activity attributable
        to it and (post-warmup) forbid implicit transfers."""
        if self._counter is None:
            raise ValueError("Alarms.dispatch outside the context "
                             "(enter the Alarms scope first)")
        warm = self._dispatches < self.warmup_iters
        amnesty, self._amnesty = self._amnesty, None
        self._dispatches += 1
        t0 = self._counter.total
        guard = (no_implicit_transfers()
                 if self.transfer_guard and not warm and amnesty is None
                 else contextlib.nullcontext())
        try:
            with guard:
                yield
        except Exception as e:
            msg = str(e)
            if "disallow" in msg.lower() or "transfer" in msg.lower():
                self._transfers.inc()
                self.bus.emit("transfer", iteration=iteration,
                              error=msg[:500])
                raise AlarmError(
                    f"implicit host<->device transfer in the iteration-"
                    f"{iteration} dispatch (transfer alarm): {msg}") from e
            raise
        compiles = self._counter.total - t0
        if compiles <= 0:
            return
        if warm or amnesty is not None:
            self.bus.emit("compile", iteration=iteration, events=compiles,
                          warmup=warm, expected=amnesty)
        else:
            self._recompiles.inc()
            self.bus.emit("recompile", iteration=iteration,
                          events=compiles)

    def observe_wall(self, iteration: int, wall_s: float) -> None:
        """Slow-iteration trigger: emit the alarm and arm a one-shot
        profiler capture of the next iteration."""
        if self.slow_iter_s is None or wall_s <= self.slow_iter_s:
            return
        self._slow.inc()
        self.bus.emit("slow_iteration", iteration=iteration,
                      wall_s=round(wall_s, 6),
                      threshold_s=self.slow_iter_s)
        if self.profile_dir is not None and not self._profile_done:
            self._profile_pending = True

    def maybe_start_profile(self) -> None:
        if not self._profile_pending or self._profile_active:
            return
        import jax
        jax.profiler.start_trace(self.profile_dir)
        self._profile_pending = False
        self._profile_active = True

    def stop_profile(self, iteration: int | None = None) -> None:
        if not self._profile_active:
            return
        import jax
        jax.profiler.stop_trace()
        self._profile_active = False
        self._profile_done = True   # one capture per run
        self.bus.emit("profile_captured", iteration=iteration,
                      profile_dir=self.profile_dir)


class RunTelemetry:
    """Everything a run loop needs, in one handle.

    >>> with RunTelemetry(obs_dir, alarms=True) as tel:
    ...     exp.run(iterations=100, log_every=10, telemetry=tel)

    The loop protocol (``Experiment.run`` / ``PopulationExperiment.run``
    implement it): ``run_start`` once; per iteration ``begin_iteration``
    → ``dispatch`` around the jitted call → phase work under
    ``sections(name)`` → ``end_iteration`` (metrics dict only when the
    loop materialized one — logged iterations); ``iteration_aborted`` on
    a rollback retry; ``run_end`` once. Everything is host-side; no
    device value is ever touched here.
    """

    def __init__(self, obs_dir: str, rank: int = 0, alarms: bool = False,
                 warmup_iters: int = 1, transfer_guard: bool = True,
                 slow_iter_s: float | None = None,
                 name: str | None = None, trace: bool = False,
                 clock: Callable[[], float] = time.monotonic):
        self.obs_dir = obs_dir
        self.bus = EventBus(obs_dir, rank=rank, name=name)
        self.registry = Registry()
        self.sections = SectionTimer()
        # the span-tracing flight recorder (obs.trace): disabled it is a
        # shared no-op context per span — the run loops thread it
        # unconditionally, so --trace costs nothing when off
        self.tracer = Tracer(self.bus, enabled=trace)
        self._clock = clock
        self.alarms = (Alarms(self.bus, self.registry,
                              warmup_iters=warmup_iters,
                              transfer_guard=transfer_guard,
                              slow_iter_s=slow_iter_s,
                              profile_dir=os.path.join(obs_dir, "profile"))
                       if alarms else None)
        self._iterations = self.registry.counter(
            "rlsched_iterations_total", "train iterations completed")
        self._env_steps = self.registry.counter(
            "rlsched_env_steps_total", "environment steps completed")
        self._steps_per_sec = self.registry.gauge(
            "rlsched_env_steps_per_sec",
            "cumulative env-steps/sec over the run (monotonic clock)")
        self._t_run = clock()
        self._t_iter: float | None = None
        self._iter_span: Any = None
        self._last_sections: dict[str, float] = {}
        self.prom_path = os.path.join(obs_dir, PROM_SNAPSHOT)

    # -- lifecycle ---------------------------------------------------------
    def __enter__(self) -> "RunTelemetry":
        if self.alarms is not None:
            self.alarms.__enter__()
        return self

    def __exit__(self, *exc) -> None:
        if self.alarms is not None:
            self.alarms.__exit__(*exc)
        self.close()

    def close(self) -> None:
        self.registry.write(self.prom_path)
        self.bus.close()

    def emit(self, kind: str, **fields: Any) -> None:
        self.bus.emit(kind, **fields)

    def run_start(self, **info: Any) -> None:
        self.bus.emit("run_start", **info)

    def run_end(self, **info: Any) -> None:
        self.bus.emit("run_end", phase_seconds=self._rounded_sections(),
                      **info)
        self.registry.write(self.prom_path)

    # -- per-iteration protocol -------------------------------------------
    def begin_iteration(self, iteration: int) -> None:
        self._t_iter = self._clock()
        if self.tracer.enabled:
            # the per-iteration flight-recorder span: phase spans the
            # loop opens (step/sync/eval/ckpt) nest under it
            self._iter_span = self.tracer.span("iteration",
                                               iteration=iteration)
            self._iter_span.__enter__()
        if self.alarms is not None:
            self.alarms.maybe_start_profile()

    def _close_iter_span(self) -> None:
        if self._iter_span is not None:
            self._iter_span.__exit__(None, None, None)
            self._iter_span = None

    @contextlib.contextmanager
    def dispatch(self, iteration: int) -> Iterator[None]:
        if self.alarms is None:
            yield
            return
        with self.alarms.dispatch(iteration):
            yield

    def end_iteration(self, iteration: int,
                      metrics: Mapping[str, Any] | None = None,
                      env_steps: int = 0) -> None:
        """Close the span opened by :meth:`begin_iteration`. ``metrics``
        is the ALREADY-materialized host dict of a logged iteration (or
        None between log points — no event, no sync, just bookkeeping)."""
        wall = (self._clock() - self._t_iter
                if self._t_iter is not None else 0.0)
        self._t_iter = None
        self._close_iter_span()
        self._iterations.inc()
        self._env_steps.inc(env_steps)
        dt = self._clock() - self._t_run
        if dt > 0:
            self._steps_per_sec.set(self._env_steps.value / dt)
        if self.alarms is not None:
            self.alarms.stop_profile(iteration)
            self.alarms.observe_wall(iteration, wall)
        if metrics is None:
            return
        self.bus.emit("iteration", iteration=iteration,
                      wall_s=round(wall, 6), phases=self._section_delta(),
                      steps_per_sec=round(self._steps_per_sec.value, 3),
                      metrics={k: v for k, v in metrics.items()})
        self.registry.write(self.prom_path)

    def iteration_aborted(self, iteration: int, reason: str) -> None:
        """A rollback retry abandoned this iteration: settle the span
        without an event (the watchdog emits its own ``rollback``) and
        grant the retry's re-trace amnesty."""
        self._t_iter = None
        self._close_iter_span()
        if self.alarms is not None:
            self.alarms.stop_profile(iteration)
            self.alarms.expect_recompile(reason)

    def expect_recompile(self, reason: str) -> None:
        if self.alarms is not None:
            self.alarms.expect_recompile(reason)

    # -- internals ---------------------------------------------------------
    def _rounded_sections(self) -> dict[str, float]:
        return {k: round(v, 6) for k, v in self.sections.report().items()}

    def _section_delta(self) -> dict[str, float]:
        """Per-phase seconds since the previous ``iteration`` event (the
        span breakdown), from the cumulative SectionTimer."""
        now = self.sections.report()
        delta = {k: round(v - self._last_sections.get(k, 0.0), 6)
                 for k, v in now.items()}
        self._last_sections = now
        for phase, secs in delta.items():
            self.registry.counter(
                f"rlsched_phase_{phase}_seconds_total",
                f"host wall seconds spent in the {phase} phase").inc(
                max(secs, 0.0))
        return delta


class OverlapMeter:
    """Online wall-clock overlap between two busy lanes (the async
    engine's actor and learner threads).

    Each lane opens/closes spans via :meth:`span`; the meter credits the
    intersection of concurrently-open spans to ``overlap_s``, each
    overlapping interval exactly once: when a span ENDS, it claims the
    intersection with the other lane's open span and advances that
    lane's credit frontier past the claimed interval, so the other
    lane's own end event cannot re-claim it. Thread-safe (one lock;
    span bookkeeping is O(1)) and clock-injectable for tests.

    This is the CI smoke stage's "nonzero overlap" evidence: even on a
    single core, the two threads' spans interleave around device waits,
    so a genuinely overlapped engine shows ``overlap_s > 0`` while a
    serialized one shows ~0.
    """

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self._lock = threading.Lock()
        self._open: dict[str, float] = {}      # lane -> actual span start
        self._frontier: dict[str, float] = {}  # lane -> uncredited start
        self.busy_s: dict[str, float] = {}
        self.overlap_s = 0.0

    @contextlib.contextmanager
    def span(self, lane: str) -> Iterator[None]:
        t0 = self._clock()
        with self._lock:
            self._open[lane] = t0
            self._frontier[lane] = t0
        try:
            yield
        finally:
            t1 = self._clock()
            with self._lock:
                start = self._open.pop(lane, t1)
                self.busy_s[lane] = (self.busy_s.get(lane, 0.0)
                                     + (t1 - start))
                mine = self._frontier.pop(lane, start)
                for other in self._open:
                    lo = max(mine, self._frontier[other])
                    if t1 > lo:
                        self.overlap_s += t1 - lo
                        self._frontier[other] = t1

    def snapshot(self) -> dict[str, float]:
        with self._lock:
            out = {f"busy_{k}_s": round(v, 6)
                   for k, v in self.busy_s.items()}
            out["overlap_s"] = round(self.overlap_s, 6)
            return out


class AsyncGauges:
    """The async engine's metric surface on a :class:`.metrics.Registry`
    (ISSUE 9 names the quartet): ``queue_depth``, ``param_staleness``,
    ``actor_idle_s``, ``learner_idle_s``, plus the overlap headline.
    Only the learner (caller) thread writes these — the actor thread
    hands its numbers over through the engine's lock-protected state, so
    the Registry never sees concurrent writers."""

    def __init__(self, registry: Registry):
        self.queue_depth = registry.gauge(
            "rlsched_async_queue_depth",
            "trajectory batches waiting in the actor->learner queue")
        self.param_staleness = registry.gauge(
            "rlsched_async_param_staleness",
            "policy-versions behind of the last consumed batch")
        self.actor_idle = registry.gauge(
            "rlsched_async_actor_idle_s",
            "cumulative seconds the actor spent blocked (staleness gate "
            "+ full-queue backpressure)")
        self.learner_idle = registry.gauge(
            "rlsched_async_learner_idle_s",
            "cumulative seconds the learner spent waiting on an empty "
            "queue")
        self.overlap = registry.gauge(
            "rlsched_async_overlap_s",
            "cumulative wall seconds actor and learner were busy "
            "simultaneously")
        self.rho_mean = registry.gauge(
            "rlsched_async_importance_ratio_mean",
            "mean unclipped importance ratio of the last logged update "
            "(1.0 = on-policy; the V-trace off-policyness monitor)")
        self.rho_max = registry.gauge(
            "rlsched_async_importance_ratio_max",
            "max unclipped importance ratio seen at any logged update "
            "this run")

    def publish(self, *, queue_depth: int, staleness: int,
                actor_idle_s: float, learner_idle_s: float,
                overlap_s: float, importance_ratio_mean: float = 1.0,
                importance_ratio_max: float = 1.0) -> None:
        self.queue_depth.set(queue_depth)
        self.param_staleness.set(staleness)
        self.actor_idle.set(round(actor_idle_s, 6))
        self.learner_idle.set(round(learner_idle_s, 6))
        self.overlap.set(round(overlap_s, 6))
        self.rho_mean.set(round(importance_ratio_mean, 6))
        self.rho_max.set(round(importance_ratio_max, 6))
