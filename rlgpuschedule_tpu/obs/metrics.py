"""Counters/gauges registry with a Prometheus-style text snapshot.

The event bus answers "what happened, when"; this registry answers "how
much, right now" — monotonically increasing counters (iterations run,
recompile alarms fired) and point-in-time gauges (steps/s). The snapshot
is the Prometheus *text exposition format* written to a file, not an
HTTP endpoint: training hosts usually can't open ports, but every fleet
scraper (node-exporter textfile collector, a sidecar, plain ``cat``)
can read a file, and the format is the observability lingua franca.

Dependency-free by the same argument as the hand-rolled TensorBoard
writer in ``utils.logging``: the write cadence is one small file per
logged iteration, so a client library would buy nothing.
"""
from __future__ import annotations

import os
import re
from typing import Union

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")


class Counter:
    """Monotonically increasing value. ``inc`` refuses negative deltas —
    a decreasing counter corrupts every rate() computed from it."""

    kind = "counter"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self.value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name}: negative increment {n}")
        self.value += n


class Gauge:
    """Point-in-time value; may move in either direction."""

    kind = "gauge"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = float(v)


class Registry:
    """Flat name -> metric registry.

    Re-registering an existing name returns the SAME object (call sites
    in different subsystems may race to declare a shared metric), but a
    kind mismatch raises — silently returning a counter where a gauge
    was requested corrupts the snapshot's TYPE line.
    """

    def __init__(self):
        self._metrics: dict[str, Union[Counter, Gauge]] = {}

    def _register(self, cls, name: str, help: str):
        if not _NAME_RE.match(name):
            raise ValueError(f"bad metric name {name!r} (want "
                             f"{_NAME_RE.pattern})")
        existing = self._metrics.get(name)
        if existing is not None:
            if not isinstance(existing, cls):
                raise ValueError(
                    f"metric {name!r} already registered as "
                    f"{existing.kind}, not {cls.kind}")
            return existing
        metric = cls(name, help)
        self._metrics[name] = metric
        return metric

    def counter(self, name: str, help: str = "") -> Counter:
        return self._register(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._register(Gauge, name, help)

    def render(self) -> str:
        """Prometheus text exposition: ``# HELP`` / ``# TYPE`` / value
        lines, name-sorted for a stable diffable snapshot."""
        lines = []
        for name in sorted(self._metrics):
            m = self._metrics[name]
            if m.help:
                lines.append(f"# HELP {name} {m.help}")
            lines.append(f"# TYPE {name} {m.kind}")
            lines.append(f"{name} {m.value:g}")
        return "\n".join(lines) + ("\n" if lines else "")

    def write(self, path: str) -> None:
        """Atomically replace the snapshot file (a scraper must never
        read a half-written exposition)."""
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(self.render())
        os.replace(tmp, path)
