"""Counters/gauges registry with a Prometheus-style text snapshot.

The event bus answers "what happened, when"; this registry answers "how
much, right now" — monotonically increasing counters (iterations run,
recompile alarms fired) and point-in-time gauges (steps/s). The snapshot
is the Prometheus *text exposition format*, delivered two ways:

- a file (``Registry.write``): training hosts usually can't open ports,
  but every fleet scraper (node-exporter textfile collector, a sidecar,
  plain ``cat``) can read a file;
- an actual scrape endpoint (:func:`serve_http`, PR 7): a serving host
  IS a network service already, so its SLO gauges are scraped live over
  HTTP — a stdlib ``http.server`` thread rendering the same exposition,
  no new dependency (closing the "snapshot to an actual scrape endpoint
  rather than files" deployment residual).

Dependency-free by the same argument as the hand-rolled TensorBoard
writer in ``utils.logging``: the write cadence is one small file per
logged iteration, so a client library would buy nothing.
"""
from __future__ import annotations

import os
import re
from typing import Union

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


def _label_suffix(labels: "dict[str, str] | None") -> str:
    """Canonical ``{k="v",...}`` rendering (sorted keys) — the identity
    of one series within a metric family. Label values may not contain
    spaces, quotes, or newlines: the exposition stays one
    whitespace-splittable ``name{labels} value`` line per series."""
    if not labels:
        return ""
    parts = []
    for k in sorted(labels):
        v = str(labels[k])
        if not _LABEL_NAME_RE.match(k):
            raise ValueError(f"bad label name {k!r} (want "
                             f"{_LABEL_NAME_RE.pattern})")
        if any(c in v for c in ' "\n\\'):
            raise ValueError(f"label {k}={v!r}: values must be free of "
                             f"spaces/quotes/backslashes/newlines")
        parts.append(f'{k}="{v}"')
    return "{" + ",".join(parts) + "}"


class Counter:
    """Monotonically increasing value. ``inc`` refuses negative deltas —
    a decreasing counter corrupts every rate() computed from it."""

    kind = "counter"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self.value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name}: negative increment {n}")
        self.value += n


class Gauge:
    """Point-in-time value; may move in either direction."""

    kind = "gauge"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = float(v)


class Histogram:
    """Prometheus histogram: cumulative ``_bucket{le=...}`` counts plus
    ``_sum``/``_count`` (text exposition format 0.0.4), so scrape-side
    ``histogram_quantile()`` computes p50/p99 across restarts and ranks
    without any in-process sample list. Buckets are fixed at
    registration (a histogram whose buckets move between scrapes is
    unaggregatable); the default ladder suits sub-second latencies.
    """

    kind = "histogram"

    DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                       0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

    def __init__(self, name: str, help: str = "",
                 buckets: tuple[float, ...] | None = None):
        self.name = name
        self.help = help
        b = tuple(float(x) for x in
                  (buckets if buckets is not None else
                   self.DEFAULT_BUCKETS))
        if not b or list(b) != sorted(b) or len(set(b)) != len(b):
            raise ValueError(f"histogram {name}: buckets must be a "
                             f"non-empty strictly increasing sequence, "
                             f"got {b}")
        self.buckets = b
        self._counts = [0] * len(b)     # per-bucket (non-cumulative)
        self.sum = 0.0
        self.count = 0

    def observe(self, v: float) -> None:
        v = float(v)
        self.sum += v
        self.count += 1
        for i, le in enumerate(self.buckets):
            if v <= le:
                self._counts[i] += 1
                break

    def cumulative(self) -> list[tuple[float, int]]:
        """``(le, cumulative_count)`` rows; the implicit ``+Inf`` bucket
        (== ``count``) is the renderer's last line."""
        out, acc = [], 0
        for le, n in zip(self.buckets, self._counts):
            acc += n
            out.append((le, acc))
        return out


def _fmt_le(le: float) -> str:
    return f"{le:g}"


class Registry:
    """Name (+ optional labels) -> metric registry.

    Re-registering an existing series returns the SAME object (call
    sites in different subsystems may race to declare a shared metric),
    but a kind mismatch raises — silently returning a counter where a
    gauge was requested corrupts the snapshot's TYPE line.

    ``labels`` (PR 13) carves one metric *family* into per-series
    values — ``serve_engine_dispatches_total{engine="1"}`` — which is
    how the multi-engine router exports per-engine occupancy without
    minting a metric name per engine (a scraper aggregates label series
    with ``sum by``; it cannot aggregate name suffixes). Labeled and
    unlabeled series may coexist under one family name; the kind and
    HELP/TYPE header are per family.
    """

    def __init__(self):
        # (name, rendered-label-suffix) -> metric; the family header
        # (kind + help) is resolved from the first-registered series
        self._metrics: dict[tuple[str, str],
                            Union[Counter, Gauge, Histogram]] = {}
        # pre-scrape collector hooks (ISSUE 20): callables run by
        # collect() before every render, so derived gauges (SLO burn
        # rates, reservoir percentiles) are recomputed at scrape time
        # instead of whenever someone last remembered to refresh them
        self._collectors: list = []
        self._in_collect = False
        self.collector_errors = 0

    def add_collector(self, fn) -> None:
        """Register a zero-arg callable to run before every render/
        scrape. Collectors refresh derived series from primary state;
        they must be cheap and must not raise (a raising collector is
        swallowed and counted in ``collector_errors`` — a broken
        refresher must never take the scrape surface down with it)."""
        if fn not in self._collectors:
            self._collectors.append(fn)

    def remove_collector(self, fn) -> None:
        """Deregister a collector (no-op if absent) — call on shutdown
        of the subsystem that owns the refreshed series."""
        try:
            self._collectors.remove(fn)
        except ValueError:
            pass

    def collect(self) -> None:
        """Run every registered collector once. Re-entrancy-guarded: a
        collector that (transitively) triggers another render observes
        the in-progress refresh instead of recursing."""
        if not self._collectors or self._in_collect:
            return
        self._in_collect = True
        try:
            for fn in list(self._collectors):
                try:
                    fn()
                except Exception:
                    self.collector_errors += 1
        finally:
            self._in_collect = False

    def _register(self, cls, name: str, help: str,
                  labels: "dict[str, str] | None" = None):
        if not _NAME_RE.match(name):
            raise ValueError(f"bad metric name {name!r} (want "
                             f"{_NAME_RE.pattern})")
        key = (name, _label_suffix(labels))
        existing = self._metrics.get(key)
        if existing is None:
            # family kind consistency: any sibling series fixes the kind
            for (n, _), m in self._metrics.items():
                if n == name and not isinstance(m, cls):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{m.kind}, not {cls.kind}")
            existing = self._metrics[key] = cls(name, help)
        elif not isinstance(existing, cls):
            raise ValueError(
                f"metric {name!r} already registered as "
                f"{existing.kind}, not {cls.kind}")
        return existing

    def counter(self, name: str, help: str = "",
                labels: "dict[str, str] | None" = None) -> Counter:
        return self._register(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: "dict[str, str] | None" = None) -> Gauge:
        return self._register(Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "",
                  buckets: tuple[float, ...] | None = None) -> Histogram:
        key = (name, "")
        existing = self._metrics.get(key)
        if existing is None:
            if not _NAME_RE.match(name):
                raise ValueError(f"bad metric name {name!r} (want "
                                 f"{_NAME_RE.pattern})")
            h = Histogram(name, help, buckets)
            self._metrics[key] = h
            return h
        if not isinstance(existing, Histogram):
            raise ValueError(f"metric {name!r} already registered as "
                             f"{existing.kind}, not histogram")
        if buckets is not None and tuple(float(x) for x in
                                         buckets) != existing.buckets:
            raise ValueError(
                f"histogram {name!r} already registered with buckets "
                f"{existing.buckets}, not {tuple(buckets)} (moving "
                f"buckets between scrapes is unaggregatable)")
        return existing

    def render(self) -> str:
        """Prometheus text exposition: ``# HELP`` / ``# TYPE`` lines per
        family, then one value line per series (label-suffixed when the
        series is labeled) or the cumulative
        ``_bucket``/``_sum``/``_count`` series per histogram;
        (name, labels)-sorted for a stable diffable snapshot. Runs the
        registered collectors first — a scrape is never stale."""
        self.collect()
        lines = []
        last_family = None
        for name, suffix in sorted(self._metrics):
            m = self._metrics[(name, suffix)]
            if name != last_family:
                last_family = name
                if m.help:
                    lines.append(f"# HELP {name} {m.help}")
                lines.append(f"# TYPE {name} {m.kind}")
            if isinstance(m, Histogram):
                for le, acc in m.cumulative():
                    lines.append(
                        f'{name}_bucket{{le="{_fmt_le(le)}"}} {acc}')
                lines.append(f'{name}_bucket{{le="+Inf"}} {m.count}')
                lines.append(f"{name}_sum {m.sum:g}")
                lines.append(f"{name}_count {m.count}")
            else:
                lines.append(f"{name}{suffix} {m.value:g}")
        return "\n".join(lines) + ("\n" if lines else "")

    def write(self, path: str) -> None:
        """Atomically replace the snapshot file (a scraper must never
        read a half-written exposition)."""
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(self.render())
        os.replace(tmp, path)


# the Prometheus text exposition content type (format version 0.0.4 —
# the plain-text lingua franca every scraper accepts)
EXPOSITION_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class MetricsHTTPServer:
    """A live scrape endpoint for one :class:`Registry`: a daemon-thread
    stdlib ``http.server`` answering ``GET /metrics`` (and ``/``) with
    the registry's current text exposition.

    Rendering happens per request under the GIL against the registry's
    plain-float metric values, so a scrape observes a consistent-enough
    point-in-time view without any locking on the hot serving path (the
    same argument the atomic file snapshot makes, minus the file).

    ``port=0`` binds an ephemeral port (tests, the ci.sh smoke stage);
    the resolved port is ``self.port``. Always ``close()`` (or use as a
    context manager) — the listener thread is daemonized but the socket
    is a real bound resource.
    """

    def __init__(self, registry: Registry, port: int = 0,
                 host: str = "127.0.0.1"):
        import http.server
        import threading

        reg = registry

        class _Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):          # noqa: N802 (http.server API)
                if self.path.split("?", 1)[0] not in ("/", "/metrics"):
                    self.send_error(404, "scrape endpoint serves /metrics")
                    return
                body = reg.render().encode("utf-8")
                self.send_response(200)
                self.send_header("Content-Type", EXPOSITION_CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):
                pass    # scrapes are periodic; stderr chatter helps nobody

        self._httpd = http.server.ThreadingHTTPServer((host, port),
                                                      _Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="metrics-scrape",
            daemon=True)
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/metrics"

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)

    def __enter__(self) -> "MetricsHTTPServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def serve_http(registry: Registry, port: int = 0,
               host: str = "127.0.0.1") -> MetricsHTTPServer:
    """Start the live scrape endpoint for ``registry``; returns the
    server (``.port`` holds the resolved port, ``.close()`` stops it)."""
    return MetricsHTTPServer(registry, port=port, host=host)
