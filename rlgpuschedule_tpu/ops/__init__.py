"""Reusable XLA-lowered ops (GAE, masked distributions)."""
from .gae import compute_gae

__all__ = ["compute_gae"]
