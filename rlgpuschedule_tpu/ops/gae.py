"""Generalized advantage estimation as a reverse lax.scan (L4 op).

Capability parity: SURVEY.md §2 "GAE". The reference computes GAE in a
Python loop over the buffer; here it lowers to one XLA scan over time
(the hardware-efficient formulation — cf. the HEPPO-GAE line of work,
SURVEY.md §7 step 5 `[P]`), fused into the jitted update.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def compute_gae(rewards: jax.Array, values: jax.Array, dones: jax.Array,
                last_value: jax.Array, gamma: float, lam: float,
                ) -> tuple[jax.Array, jax.Array]:
    """Returns (advantages, returns), each [T, ...].

    Args:
      rewards: [T, ...] reward at each step.
      values:  [T, ...] value estimate of the state the action was taken in.
      dones:   [T, ...] episode ended AT this step (auto-reset envs: the
               next state belongs to a fresh episode — no bootstrap across).
      last_value: [...] value of the state after the final step.
    """
    def step(next_adv_and_v, x):
        next_adv, next_v = next_adv_and_v
        r, v, d = x
        nonterm = 1.0 - d
        delta = r + gamma * next_v * nonterm - v
        adv = delta + gamma * lam * nonterm * next_adv
        return (adv, v), adv

    (_, _), advantages = jax.lax.scan(
        step, (jnp.zeros_like(last_value), last_value),
        (rewards, values, dones.astype(rewards.dtype)), reverse=True)
    return advantages, advantages + values
