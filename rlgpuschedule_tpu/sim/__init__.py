"""L1 simulator layer: exact Python oracle + jit/vmap JAX core + the
seeded cluster fault process (chaos engine)."""
from .faults import (FAULT_REGIMES, FaultRegime, FaultSchedule,
                     fault_schedule_from_events, no_faults,
                     sample_fault_schedule, sample_env_fault_schedules,
                     stack_fault_schedules, validate_fault_schedule)
from .oracle import (OracleSim, pack_placement, spread_placement,
                     NOT_ARRIVED, PENDING, RUNNING, DONE, PACK, SPREAD)
from .schedulers import (SchedulerPolicy, fifo, sjf, srtf, tiresias,
                         BASELINES, run_scheduler, evaluate_baselines)

__all__ = [
    "FAULT_REGIMES", "FaultRegime", "FaultSchedule",
    "fault_schedule_from_events", "no_faults", "sample_fault_schedule",
    "sample_env_fault_schedules", "stack_fault_schedules",
    "validate_fault_schedule",
    "OracleSim", "pack_placement", "spread_placement",
    "NOT_ARRIVED", "PENDING", "RUNNING", "DONE", "PACK", "SPREAD",
    "SchedulerPolicy", "fifo", "sjf", "srtf", "tiresias",
    "BASELINES", "run_scheduler", "evaluate_baselines",
]
