"""L1 simulator layer: exact Python oracle + jit/vmap JAX core."""
from .oracle import (OracleSim, pack_placement, spread_placement,
                     NOT_ARRIVED, PENDING, RUNNING, DONE, PACK, SPREAD)
from .schedulers import (SchedulerPolicy, fifo, sjf, srtf, tiresias,
                         BASELINES, run_scheduler, evaluate_baselines)

__all__ = [
    "OracleSim", "pack_placement", "spread_placement",
    "NOT_ARRIVED", "PENDING", "RUNNING", "DONE", "PACK", "SPREAD",
    "SchedulerPolicy", "fifo", "sjf", "srtf", "tiresias",
    "BASELINES", "run_scheduler", "evaluate_baselines",
]
