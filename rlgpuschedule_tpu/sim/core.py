"""Pure-functional, jit/vmap-able cluster simulator (L1) — the TPU hot path.

Capability parity: SURVEY.md §1 L1/L2 TPU restatement — "the discrete-event
GPU-cluster simulator becomes a jit-compiled, vmapped environment". This is
the central rebuild challenge (SURVEY.md §7 step 2 and "hard parts" (a)):

- State is a pytree of **fixed-shape** arrays (static shapes for XLA): a job
  table ``[J]`` with status masks, a per-job allocation matrix ``[J, N]``,
  a free-GPU vector ``[N]``, and a scalar clock.
- The reference's Python priority queue is replaced by **masked argmin over
  next-event times** — O(J) but fully vectorized, which is the idiomatic
  TPU trade (SURVEY.md §7 step 2).
- Every function here is a pure ``state -> state`` map built from
  ``jnp.where`` masks — no data-dependent Python control flow, so the whole
  step jits once and ``vmap``s over an env batch.

Semantics are specified by ``sim.oracle.OracleSim`` and enforced by the
property tests in ``tests/test_sim_core.py`` (bit-identical schedules on
integer-valued traces).
"""
from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..traces.records import ArrayTrace
from .faults import (FaultSchedule, effective_free, job_stretch, next_transition,
                     node_up, validate_fault_schedule)
from .oracle import NOT_ARRIVED, PENDING, RUNNING, DONE, PACK, SPREAD

INF = jnp.inf
_EPS = 1e-5  # completion tolerance in float32 virtual time


@dataclasses.dataclass(frozen=True)
class SimParams:
    """Static simulator configuration (hashable; closed over by jit)."""
    n_nodes: int
    gpus_per_node: int
    max_jobs: int          # J: rows in the (padded) job table
    queue_len: int = 16    # K: pending-queue slots visible to the agent
    n_placements: int = 1  # P: 1 = pack only; 2 = pack|spread factored action
    preempt_len: int = 0   # R: running-job slots the agent may preempt
    #                        (0 = non-preemptive action space, the default)

    @property
    def capacity(self) -> int:
        return self.n_nodes * self.gpus_per_node

    @property
    def n_actions(self) -> int:
        # [K*P placements][R preemptions][no-op] — see rl_step
        return self.queue_len * self.n_placements + self.preempt_len + 1


class Trace(NamedTuple):
    """Device-side trace (rows sorted by submit; padding has submit=+inf)."""
    submit: jax.Array    # f32[J]
    duration: jax.Array  # f32[J]
    gpus: jax.Array      # i32[J]
    tenant: jax.Array    # i32[J]
    valid: jax.Array     # bool[J]

    @staticmethod
    def from_array_trace(tr: ArrayTrace, params: "SimParams | None" = None,
                         ) -> "Trace":
        """Upload a host trace; pass ``params`` to validate gang sizes
        against cluster capacity (recommended — see :func:`validate_trace`)."""
        if params is not None:
            tr = validate_trace(params, tr)
        return Trace(jnp.asarray(tr.submit), jnp.asarray(tr.duration),
                     jnp.asarray(tr.gpus), jnp.asarray(tr.tenant),
                     jnp.asarray(tr.valid))


def validate_trace(params: SimParams, tr: ArrayTrace, clamp: bool = False,
                   faults: "FaultSchedule | None" = None) -> ArrayTrace:
    """Host-side guard mirroring OracleSim's constructor check: a valid job
    demanding more GPUs than the cluster has can never be placed, and inside
    the jitted sim that surfaces as a silently frozen episode (no exception
    can be raised from traced code). Raise here instead — or, with
    ``clamp=True``, cap demands at capacity (useful when replaying a big
    production trace on a small debug cluster).

    ``faults``: also validate a fault schedule against the cluster shape
    (drain windows sorted, durations positive, node count matching — see
    :func:`~.faults.validate_fault_schedule`), so the trace and its chaos
    script are vetted at the same ingest point."""
    if faults is not None:
        validate_fault_schedule(params.n_nodes, faults)
    over = tr.valid & (tr.gpus > params.capacity)
    if not over.any():
        return tr
    if not clamp:
        raise ValueError(
            f"{int(over.sum())} job(s) demand more than the cluster's "
            f"{params.capacity} GPUs (max demand {int(tr.gpus[tr.valid].max())}); "
            f"pass clamp=True to cap demands at capacity")
    gpus = np.minimum(tr.gpus, params.capacity)
    return dataclasses.replace(tr, gpus=gpus)


class SimState(NamedTuple):
    """Dynamic simulator state — a pytree of fixed-shape arrays."""
    clock: jax.Array      # f32 scalar
    status: jax.Array     # i32[J]
    remaining: jax.Array  # f32[J]
    start: jax.Array      # f32[J] (+inf until started)
    finish: jax.Array     # f32[J] (+inf until done)
    alloc: jax.Array      # i32[J, N]
    free: jax.Array       # i32[N]


class StepInfo(NamedTuple):
    """Per-step outcomes consumed by rewards/metrics."""
    placed: jax.Array           # bool — action placed a job this step
    dt: jax.Array               # f32 — simulated time advanced
    in_system_before: jax.Array # i32 — arrived-not-done count during [t, t+dt)
    done: jax.Array             # bool — all valid jobs DONE
    preempted: jax.Array        # bool — action preempted a running job
    first_placed: jax.Array     # bool — placed a job that had NEVER run
    #   (drives place_bonus: re-placing a preempted job earns nothing, so
    #    the shaping potential Φ = bonus·#{ever-started} still telescopes
    #    and a preempt→re-place cycle cannot farm reward)


# ---- lifecycle --------------------------------------------------------------

def init_state(params: SimParams, trace: Trace,
               faults: "FaultSchedule | None" = None) -> SimState:
    J, N = params.max_jobs, params.n_nodes
    # a DomainSchedule (domains.schedule) carries per-node GPU capacity as
    # data — geometry randomization without retracing; a plain
    # FaultSchedule (or None) has no capacity attribute and the free
    # vector stays the bit-identical static full cluster
    cap = getattr(faults, "capacity", None)
    free = (jnp.full((N,), params.gpus_per_node, jnp.int32) if cap is None
            # copy=True for the same donation-aliasing reason as remaining
            else jnp.array(cap, jnp.int32, copy=True))
    state = SimState(
        clock=jnp.float32(0.0),
        status=jnp.where(trace.valid, NOT_ARRIVED, DONE).astype(jnp.int32),
        # copy=True: .astype on an already-f32 array aliases the trace
        # buffer, and a donated sim state must never share buffers with the
        # (non-donated) trace — XLA rejects `f(donate(a), a)`
        remaining=jnp.array(trace.duration, jnp.float32, copy=True),
        start=jnp.full((J,), INF, jnp.float32),
        finish=jnp.full((J,), INF, jnp.float32),
        alloc=jnp.zeros((J, N), jnp.int32),
        free=free,
    )
    return _process_arrivals(state, trace)


def _process_arrivals(state: SimState, trace: Trace) -> SimState:
    arrived = (state.status == NOT_ARRIVED) & (trace.submit <= state.clock)
    return state._replace(
        status=jnp.where(arrived, PENDING, state.status))


# ---- events -----------------------------------------------------------------

def next_event_time(state: SimState, trace: Trace,
                    faults: "FaultSchedule | None" = None) -> jax.Array:
    """Earliest future arrival, completion, or fault transition; +inf if
    none (masked min — the vectorized replacement for the oracle's
    priority queue). With ``faults``, completions are slowdown-stretched
    (a gang finishes at ``clock + remaining × stretch``) and every drain
    start / node return is an event, so the decision loop stops AT each
    transition and :func:`advance_to` never integrates across one."""
    arrival = jnp.min(jnp.where(state.status == NOT_ARRIVED, trace.submit, INF))
    running = state.status == RUNNING
    if faults is None:
        eta = state.clock + state.remaining
    else:
        eta = state.clock + state.remaining * job_stretch(faults, state.alloc)
    completion = jnp.min(jnp.where(running, eta, INF))
    t = jnp.minimum(arrival, completion)
    if faults is not None:
        t = jnp.minimum(t, next_transition(faults, state.clock))
    return t


def advance_to(state: SimState, trace: Trace, t: jax.Array,
               faults: "FaultSchedule | None" = None) -> SimState:
    """Advance the clock to ``t`` (caller guarantees t ≤ next event; +inf is
    a no-op). Completions at ``t`` are processed before arrivals, matching
    ``OracleSim.advance_to``.

    With ``faults``: running work progresses at ``1/stretch`` (straggler
    nodes stretch remaining service; ``next_event_time`` uses the same
    stretched expression, so the completion-tolerance argument below is
    unchanged), and — after completions, before arrivals — every job still
    holding an allocation on a node that is down at ``t`` is killed back
    to PENDING with its attained service preserved (checkpointed
    preemption; the job is never lost). The caller contract "t ≤ next
    event" now also means "never advance across a fault transition":
    ``next_event_time`` includes transitions, so ``rl_step`` stops at the
    drain instant and the kill happens exactly there."""
    finite = jnp.isfinite(t)
    t = jnp.where(finite, t, state.clock)
    dt = t - state.clock
    running = state.status == RUNNING
    if faults is None:
        progressed = state.remaining - dt
        eta = state.clock + state.remaining
    else:
        stretch = job_stretch(faults, state.alloc)
        progressed = state.remaining - dt / stretch
        eta = state.clock + state.remaining * stretch
    remaining = jnp.where(running, jnp.maximum(progressed, 0.0),
                          state.remaining)
    # Completion test on absolute completion time with an ulp-scaled
    # tolerance: at large clocks the f32 spacing of ``clock + remaining``
    # exceeds any absolute epsilon, so ``remaining - dt`` can round to a
    # small positive value while next_event_time rounds to the current
    # clock — a dt=0 deadlock. A few ulps of ``t`` covers the worst-case
    # rounding of the sum without opening an early-completion window wider
    # than f32 time resolution itself (1e-5·|t| would complete jobs seconds
    # early on Philly-scale clocks).
    tol = _EPS + 4.0 * jnp.spacing(t)
    completed = running & (eta <= t + tol)
    released = jnp.sum(state.alloc * completed[:, None].astype(jnp.int32), axis=0)
    state = SimState(
        clock=t,
        status=jnp.where(completed, DONE, state.status),
        remaining=jnp.where(completed, 0.0, remaining),
        start=state.start,
        finish=jnp.where(completed, t, state.finish),
        alloc=jnp.where(completed[:, None], 0, state.alloc),
        free=state.free + released,
    )
    if faults is not None:
        state = _kill_drained(state, faults)
    return _process_arrivals(state, trace)


def _kill_drained(state: SimState, faults: FaultSchedule) -> SimState:
    """RUNNING → PENDING for every job holding an allocation on a node
    that is down at ``state.clock``; GPUs return to ``free`` so the
    per-node conservation invariant (free + allocated == capacity) holds
    at every instant. Idempotent and branch-free: a pure mask over
    (alloc, node_up) — re-applying it at a later step while the node is
    still down is a no-op because killed jobs hold no allocation."""
    up = node_up(faults, state.clock)
    killed = (state.status == RUNNING) & jnp.any(
        (state.alloc > 0) & ~up[None, :], axis=1)
    released = jnp.sum(state.alloc * killed[:, None].astype(jnp.int32),
                       axis=0)
    return state._replace(
        status=jnp.where(killed, PENDING, state.status),
        alloc=jnp.where(killed[:, None], 0, state.alloc),
        free=state.free + released,
    )


# ---- placement (matches oracle.pack_placement / spread_placement) ----------

def pack_placement(free: jax.Array, demand: jax.Array,
                   ) -> tuple[jax.Array, jax.Array]:
    """Fill freest nodes first (ties → lowest node id). Returns (alloc[N],
    feasible). jnp.argsort is stable, so argsort(-free) reproduces the
    oracle's (free desc, id asc) order."""
    feasible = demand <= jnp.sum(free)
    order = jnp.argsort(-free)
    sorted_free = free[order]
    before = jnp.cumsum(sorted_free) - sorted_free
    take = jnp.clip(demand - before, 0, sorted_free)
    alloc = jnp.zeros_like(free).at[order].set(take)
    return jnp.where(feasible, alloc, 0), feasible


def spread_placement(free: jax.Array, demand: jax.Array, gpus_per_node: int,
                     ) -> tuple[jax.Array, jax.Array]:
    """Water-filling: smallest level t with Σ min(free, t) ≥ demand;
    excess trimmed from the highest node ids allocated exactly t."""
    feasible = demand <= jnp.sum(free)
    levels = jnp.arange(gpus_per_node + 1)                      # [G+1]
    supply = jnp.sum(jnp.minimum(free[None, :], levels[:, None]), axis=1)
    t = jnp.argmax(supply >= demand)                            # first true
    alloc = jnp.minimum(free, t)
    excess = jnp.sum(alloc) - demand
    at_t = alloc == t
    # rank 1.. from the highest node id among nodes at level t
    rank_from_top = jnp.cumsum(at_t[::-1].astype(jnp.int32))[::-1]
    trim = at_t & (rank_from_top <= excess)
    alloc = jnp.where(trim, alloc - 1, alloc)
    return jnp.where(feasible, alloc, 0), feasible


def placement(free: jax.Array, demand: jax.Array, mode: jax.Array,
              gpus_per_node: int, n_placements: int = 2,
              ) -> tuple[jax.Array, jax.Array]:
    """Traced-mode dispatch between pack (0) and spread (1). When the action
    space has a single placement (``n_placements == 1``, a static Python
    int), the spread branch is dropped at trace time — no dead water-filling
    compute in the jitted hot path."""
    pa, pf = pack_placement(free, demand)
    if n_placements == 1:
        return pa, pf
    sa, sf = spread_placement(free, demand, gpus_per_node)
    spread = mode == SPREAD
    return jnp.where(spread, sa, pa), jnp.where(spread, sf, pf)


# ---- scheduling actions -----------------------------------------------------

def try_place(params: SimParams, state: SimState, trace: Trace,
              j: jax.Array, mode: jax.Array,
              faults: "FaultSchedule | None" = None,
              ) -> tuple[SimState, jax.Array]:
    """Gang-place job row ``j`` (traced index; -1 = invalid). Returns
    (state', success). All-or-nothing: infeasible → state unchanged.
    With ``faults``, placement sees drained nodes as zero free capacity
    (:func:`~.faults.effective_free`), so a gang can never land on a
    down node."""
    jc = jnp.clip(j, 0, params.max_jobs - 1)
    pending = (j >= 0) & (state.status[jc] == PENDING)
    demand = trace.gpus[jc]
    free = effective_free(faults, state.free, state.clock)
    alloc, feasible = placement(free, demand, mode, params.gpus_per_node,
                                params.n_placements)
    ok = pending & feasible
    allocd = jnp.where(ok, alloc, 0)
    row = jax.nn.one_hot(jc, params.max_jobs, dtype=jnp.int32) * ok.astype(jnp.int32)
    return SimState(
        clock=state.clock,
        status=jnp.where(row.astype(bool), RUNNING, state.status),
        remaining=state.remaining,
        start=jnp.where(row.astype(bool),
                        jnp.minimum(state.start, state.clock), state.start),
        finish=state.finish,
        alloc=state.alloc + row[:, None] * allocd[None, :],
        free=state.free - allocd,
    ), ok


def preempt(state: SimState, j: jax.Array, max_jobs: int
            ) -> tuple[SimState, jax.Array]:
    """RUNNING → PENDING for job row ``j``; attained service preserved."""
    jc = jnp.clip(j, 0, max_jobs - 1)
    ok = (j >= 0) & (state.status[jc] == RUNNING)
    row = (jax.nn.one_hot(jc, max_jobs, dtype=jnp.int32) * ok.astype(jnp.int32)
           ).astype(bool)
    released = jnp.sum(state.alloc * row[:, None].astype(jnp.int32), axis=0)
    return state._replace(
        status=jnp.where(row, PENDING, state.status),
        alloc=jnp.where(row[:, None], 0, state.alloc),
        free=state.free + released,
    ), ok


# ---- queue & queries --------------------------------------------------------

def pending_queue(params: SimParams, state: SimState) -> jax.Array:
    """Row indices of the first K pending jobs, -1 padded. Trace rows are
    submit-sorted at construction, so row order IS the oracle's
    (submit asc, id asc) queue order."""
    K = params.queue_len
    pending = state.status == PENDING
    rank = jnp.cumsum(pending.astype(jnp.int32)) - 1
    rows = jnp.arange(params.max_jobs, dtype=jnp.int32)
    target = jnp.where(pending & (rank < K), rank, K)  # K = scatter-drop slot
    return jnp.full((K + 1,), -1, jnp.int32).at[target].set(
        jnp.where(pending & (rank < K), rows, -1), mode="drop")[:K]


def running_queue(params: SimParams, state: SimState, trace: Trace,
                  ) -> jax.Array:
    """Row indices of the R running jobs with the MOST attained GPU-service
    (ties → lowest row id), -1 padded — the slots the preemptive action
    space indexes into. Most-served-first is the Tiresias demotion order:
    preempting slot 0 evicts the long-runner to make room for short work
    (attained service is preserved, so nothing is lost)."""
    R = params.preempt_len
    running = state.status == RUNNING
    key = jnp.where(running, attained_service(state, trace), -INF)
    order = jnp.argsort(-key)                  # stable: ties → row asc
    rows = order[:R].astype(jnp.int32)
    # NOTE: the sort key is f32 (device state) while OracleSim.running_queue
    # sorts in f64; the bit-identical-equivalence contract therefore holds
    # on integer-valued traces (where f32 time is exact — the property-test
    # regime, tests/test_sim_core.py), not on arbitrary float traces where
    # two attained-service values may tie in f32 but differ in f64.
    return jnp.where(running[rows], rows, -1)


def in_system(state: SimState) -> jax.Array:
    return jnp.sum((state.status == PENDING) | (state.status == RUNNING))


def all_done(state: SimState, trace: Trace) -> jax.Array:
    return jnp.all(jnp.where(trace.valid, state.status == DONE, True))


def attained_service(state: SimState, trace: Trace) -> jax.Array:
    """Per-job attained GPU-seconds (Tiresias priority key)."""
    executed = trace.duration - state.remaining
    return executed * trace.gpus.astype(jnp.float32)


def action_mask(params: SimParams, state: SimState, trace: Trace,
                queue: jax.Array | None = None,
                run_queue: jax.Array | None = None,
                faults: "FaultSchedule | None" = None) -> jax.Array:
    """bool[n_actions]: queue-slot actions valid iff the slot holds a pending
    job whose gang fits in the free GPUs (pack and spread share feasibility:
    jobs may span nodes); preempt slots valid iff they hold a running job;
    no-op is always valid. Pass precomputed ``pending_queue`` /
    ``running_queue`` to share them with the observation builder. With
    ``faults``, feasibility counts only up nodes' free GPUs — the mask and
    :func:`try_place` always agree on what fits."""
    if queue is None:
        queue = pending_queue(params, state)                   # [K]
    jc = jnp.clip(queue, 0, params.max_jobs - 1)
    demand = trace.gpus[jc]
    free = effective_free(faults, state.free, state.clock)
    ok = (queue >= 0) & (demand <= jnp.sum(free))              # [K]
    slots = jnp.repeat(ok, params.n_placements)                # [K*P]
    parts = [slots]
    if params.preempt_len:
        if run_queue is None:
            run_queue = running_queue(params, state, trace)    # [R]
        parts.append(run_queue >= 0)
    parts.append(jnp.ones((1,), bool))
    return jnp.concatenate(parts)


# ---- the RL decision-point step --------------------------------------------

def rl_step(params: SimParams, state: SimState, trace: Trace,
            action: jax.Array, faults: "FaultSchedule | None" = None,
            ) -> tuple[SimState, StepInfo]:
    """One decision-point step; exact jit/vmap analogue of
    ``OracleSim.rl_step`` (see its docstring for the semantics). Branchless:
    every outcome (placement vs preemption vs time-advance) is computed and
    masked — the idiomatic XLA trade against host control flow.

    Action layout: ``[K*P placements][R preemptions][no-op]``. Placements
    and preemptions cost no simulated time (the agent acts again at the
    same instant); preemption targets ``running_queue`` slots. The R block
    exists only when ``params.preempt_len > 0``, so non-preemptive configs
    trace the exact same XLA program as before.

    ``faults`` (a :class:`~.faults.FaultSchedule`, or None = permanently
    healthy) threads the cluster fault process through placement
    feasibility, event selection, progress stretching, and drain kills —
    it is DATA: stepping under a different schedule of the same shape
    reuses the compiled program (CompileCounter-asserted)."""
    K, P, R = params.queue_len, params.n_placements, params.preempt_len
    n_place = K * P
    queue = pending_queue(params, state)
    is_place = action < n_place
    k = jnp.clip(action // P, 0, K - 1)
    mode = action % P
    j = jnp.where(is_place, queue[k], -1)

    placed_state, placed = try_place(params, state, trace, j, mode, faults)

    if R:
        run_q = running_queue(params, state, trace)
        is_pre = ~is_place & (action < n_place + R)
        r = jnp.clip(action - n_place, 0, R - 1)
        pre_state, preempted = preempt(
            state, jnp.where(is_pre, run_q[r], -1), params.max_jobs)
    else:
        preempted = jnp.bool_(False)
    progress = placed | preempted

    # no progress → advance to next event, or force-place queue head if the
    # event horizon is empty (nothing running ⇒ cluster free ⇒ feasible for
    # any job with demand ≤ capacity — validate_trace enforces that on host;
    # an over-capacity job would make forced_ok False and the episode can
    # only end via the env horizon). Under faults an exhausted event
    # horizon additionally implies no transition is pending, so any still-
    # drained node is drained FOREVER; a job that no longer fits the
    # surviving capacity makes forced_ok False the same way.
    t_next = next_event_time(state, trace, faults)
    has_event = jnp.isfinite(t_next)
    n_before = in_system(state)
    advanced_state = advance_to(state, trace, t_next, faults)
    forced_state, forced_ok = try_place(params, state, trace, queue[0],
                                        jnp.int32(PACK), faults)

    if R:
        def pick(a, p, b, c):
            # placed ? a : preempted ? p : (has_event ? b : c)
            return jnp.where(placed, a, jnp.where(
                preempted, p, jnp.where(has_event, b, c)))

        new_state = jax.tree.map(pick, placed_state, pre_state,
                                 advanced_state, forced_state)
    else:
        def pick(a, b, c):  # placed ? a : (has_event ? b : c)
            return jnp.where(placed, a, jnp.where(has_event, b, c))

        new_state = jax.tree.map(pick, placed_state, advanced_state,
                                 forced_state)
    dt = jnp.where(progress | ~has_event, 0.0, t_next - state.clock)
    # "first" = the job had never run before this step (start still +inf);
    # try_place keeps the original start on re-placement, so this reads the
    # pre-step state
    never_ran = ~jnp.isfinite(state.start)
    first_sel = never_ran[jnp.clip(j, 0, params.max_jobs - 1)]
    first_head = never_ran[jnp.clip(queue[0], 0, params.max_jobs - 1)]
    forced_fire = ~progress & ~has_event & forced_ok
    info = StepInfo(placed=placed | forced_fire,
                    dt=dt, in_system_before=n_before,
                    done=all_done(new_state, trace),
                    preempted=preempted,
                    first_placed=(placed & first_sel)
                    | (forced_fire & first_head))
    return new_state, info


# ---- metrics ----------------------------------------------------------------

def jct_stats(state: SimState, trace: Trace) -> dict[str, jax.Array]:
    """Avg/max JCT over completed valid jobs (masked)."""
    done = trace.valid & (state.status == DONE)
    jct = jnp.where(done, state.finish - trace.submit, 0.0)
    n = jnp.maximum(jnp.sum(done), 1)
    return {"avg_jct": jnp.sum(jct) / n,
            "max_jct": jnp.max(jnp.where(done, jct, -INF)),
            "n_done": jnp.sum(done)}


def utilization(params: SimParams, state: SimState) -> jax.Array:
    return 1.0 - jnp.sum(state.free) / params.capacity


def np_state(state: SimState) -> SimState:
    """Host copy for debugging/tests."""
    return jax.tree.map(np.asarray, state)
