"""Baseline schedulers on the oracle sim (L1/L6).

Capability parity: SURVEY.md §2 "Baseline schedulers" — a Tiresias-like
discretized two-dimensional LAS scheduler (the reference's comparison
baseline, `[B]`) plus FIFO/SJF/SRTF for the eval tables (`[K]`).

All baselines share one event loop (:func:`run_scheduler`): at every event the
scheduler produces a priority ordering over in-system jobs; the loop then
greedily admits jobs in that order while the gang fits, preempting (if the
policy is preemptive) any running job that fell out of the admitted set. This
uniform mechanism is itself a correctness check on the oracle — FIFO/SJF JCTs
on tiny traces are hand-verifiable (SURVEY.md §4 "Baseline-scheduler oracle
tests").
"""
from __future__ import annotations

import dataclasses
from typing import Callable, Protocol, Sequence, runtime_checkable

import numpy as np

from .oracle import OracleSim, PACK, PENDING, RUNNING


@runtime_checkable
class BaselineResult(Protocol):
    """The finished-run surface every ``run_baseline`` backend returns.

    Both ``OracleSim`` (python backend) and ``native.NativeSimResult``
    (C++ backend) satisfy this, so callers can depend on it regardless of
    which backend a host selects (ADVICE r1: backend='auto' previously
    returned divergent surfaces, failing only on compiler-equipped
    machines)."""
    trace: "object"
    finish: np.ndarray   # per-row completion times (NaN/inf on padding)
    start: np.ndarray    # per-row FIRST start times
    status: np.ndarray   # oracle status codes (DONE for completed jobs)

    def jcts(self) -> np.ndarray: ...
    def avg_jct(self) -> float: ...


@dataclasses.dataclass
class SchedulerPolicy:
    """A baseline: a priority key over in-system jobs + preemption flag.

    ``key(sim, j)`` — lower sorts first. Non-preemptive policies keep running
    jobs running unconditionally and only order the pending queue.
    ``next_wake(sim)`` — earliest future instant at which the policy's
    priorities change *between* events (e.g. a Tiresias queue demotion);
    the event loop advances to min(next event, next wake).
    """
    name: str
    key: Callable[[OracleSim, int], tuple]
    preemptive: bool = False
    next_wake: Callable[[OracleSim], float] = lambda s: float("inf")


def fifo() -> SchedulerPolicy:
    return SchedulerPolicy("fifo", lambda s, j: (s.trace.submit[j], j))


def sjf() -> SchedulerPolicy:
    """Shortest job first (non-preemptive, by total service demand)."""
    return SchedulerPolicy("sjf", lambda s, j: (s.trace.duration[j], j))


def srtf() -> SchedulerPolicy:
    """Shortest remaining time first (preemptive)."""
    return SchedulerPolicy("srtf", lambda s, j: (s.remaining[j], j), preemptive=True)


def tiresias(thresholds: Sequence[float] = (3600.0, 36000.0)) -> SchedulerPolicy:
    """Tiresias-like discretized 2D-LAS (`[B]` baseline; design per the
    Tiresias NSDI'19 scheme, `[K]`): priority = attained GPU-service
    (gpus × executed seconds) discretized into queues by ``thresholds``;
    within a queue, FIFO by submit time. Preemptive: newly-arrived jobs sit in
    the highest queue and can preempt demoted long-running jobs. The 2D part
    is exactly that service is *GPU-time*, so wide gangs demote sooner."""
    th = np.asarray(sorted(thresholds), np.float64)

    def key(s: OracleSim, j: int):
        q = int(np.searchsorted(th, s.attained_service(j), side="right"))
        return (q, s.trace.submit[j], j)

    def next_wake(s: OracleSim) -> float:
        """Earliest instant a running job's attained GPU-service crosses its
        next demotion threshold."""
        t = float("inf")
        for j in s.running_jobs():
            a = s.attained_service(j)
            nxt = th[np.searchsorted(th, a, side="right"):]
            if len(nxt):
                t = min(t, s.clock + (float(nxt[0]) - a) / float(s.trace.gpus[j]))
        return t

    return SchedulerPolicy("tiresias", key, preemptive=True, next_wake=next_wake)


BASELINES: dict[str, Callable[[], SchedulerPolicy]] = {
    "fifo": fifo, "sjf": sjf, "srtf": srtf, "tiresias": tiresias,
}


def schedule_step(sim: OracleSim, policy: SchedulerPolicy,
                  placement: int = PACK) -> None:
    """Apply one scheduling decision round at the current instant."""
    if policy.preemptive:
        insys = [j for j in range(sim.trace.max_jobs)
                 if sim.status[j] in (PENDING, RUNNING)]
        order = sorted(insys, key=lambda j: policy.key(sim, j))
        # Greedy prefix admission: walk the priority order, keep/place while
        # the gang fits. Anything running but not admitted is preempted first
        # so its GPUs are available to higher-priority jobs.
        # effective_free: drained nodes offer no capacity (running gangs
        # only ever occupy up nodes — drains evict them at the transition)
        budget = int(sim.effective_free().sum()) + \
            sum(int(sim.trace.gpus[j]) for j in sim.running_jobs())
        admitted = []
        for j in order:
            d = int(sim.trace.gpus[j])
            if d <= budget:
                admitted.append(j)
                budget -= d
        admitted_set = set(admitted)
        for j in sim.running_jobs():
            if j not in admitted_set:
                sim.preempt(j)
        for j in admitted:
            if sim.status[j] == PENDING:
                sim.try_place(j, placement)
    else:
        for j in sorted(sim.pending_jobs(), key=lambda j: policy.key(sim, j)):
            sim.try_place(j, placement)


def run_scheduler(sim: OracleSim, policy: SchedulerPolicy,
                  placement: int = PACK, max_events: int = 10_000_000) -> OracleSim:
    """Run ``policy`` to trace completion; returns the finished sim."""
    sim.reset()
    for _ in range(max_events):
        schedule_step(sim, policy, placement)
        if sim.done():
            return sim
        t = min(sim.next_event_time(), policy.next_wake(sim))
        if not np.isfinite(t):
            raise RuntimeError("scheduler deadlock: pending jobs but no events")
        if sim.advance_to(t) <= 0.0 and not sim.done():
            # zero-dt wake (threshold exactly at clock): avoid spinning
            if sim.advance_to_next_event() == 0.0:
                raise RuntimeError("scheduler made no progress")
    raise RuntimeError("max_events exceeded")


def run_baseline(trace, n_nodes: int, gpus_per_node: int, name: str,
                 backend: str = "auto", faults=None) -> BaselineResult:
    """Run one named baseline over a trace; returns the finished sim (the
    single implementation behind every baseline JCT table).

    ``backend``: "auto" uses the C++ engine (``rlgpuschedule_tpu.native``,
    ~100× the Python oracle on production-scale traces) when a toolchain is
    present, falling back to the oracle; "python" / "native" force one.
    Both backends implement identical semantics (cross-validated in
    tests/test_native.py) and return the :class:`BaselineResult` surface.

    ``faults`` (a :class:`~.faults.FaultSchedule`) runs the baseline on a
    faulty cluster — the chaos matrix's apples-to-apples comparison
    against the policy replayed under the SAME schedule. The native
    engine has no fault model, so faults force the Python oracle
    (``backend="native"`` + faults is refused rather than silently
    diverging)."""
    if backend not in ("auto", "python", "native"):
        raise ValueError(f"unknown backend {backend!r}")
    if faults is not None and backend == "native":
        raise ValueError("the native backend has no fault model; run "
                         "faulty-cluster baselines on the python oracle")
    if backend != "python" and faults is None:
        from .. import native
        if native.available():
            from ..traces.records import ArrayTrace, to_array_trace
            tr = trace if isinstance(trace, ArrayTrace) else \
                to_array_trace(trace)
            finish, start = native.run_baseline_native(
                tr, n_nodes, gpus_per_node, name)
            return native.NativeSimResult(tr, finish, start)
        if backend == "native":
            raise RuntimeError(
                f"native backend unavailable: {native.build_error()}")
    sim = OracleSim(trace, n_nodes, gpus_per_node, faults=faults)
    return run_scheduler(sim, BASELINES[name]())


def evaluate_baselines(trace, n_nodes: int, gpus_per_node: int,
                       names: Sequence[str] = ("fifo", "sjf", "srtf", "tiresias"),
                       ) -> dict[str, float]:
    """Avg-JCT table for the requested baselines on one trace."""
    return {name: run_baseline(trace, n_nodes, gpus_per_node, name).avg_jct()
            for name in names}
