"""Seeded, jit-pure cluster fault process (L1) — the in-simulator chaos
engine.

``sim.core`` models nodes as permanently healthy capacity; this module
makes node failure a first-class, *data-driven* part of the simulation:

- :class:`FaultSchedule` — a precomputed, trace-like pytree of per-node
  drain windows and slowdown factors. Like :class:`~.core.Trace`, it is
  DATA, not code: the jitted step takes it as an argument, so stepping
  under two different schedules of the same shape traces and compiles
  exactly once (the Jumanji scalable-env recipe — randomize over a fault
  distribution without touching the XLA program; CompileCounter-asserted
  in tests/test_sim_faults.py).
- branch-free consumption helpers (:func:`node_up`,
  :func:`next_transition`, :func:`job_stretch`) that ``core.advance_to``
  / ``core.try_place`` fold into their existing ``jnp.where`` masks, so
  ``jit``/``vmap``/``scan`` and the vec-env keep working unchanged.
- seeded host-side *regimes* (:data:`FAULT_REGIMES`) — none / sporadic
  drains / correlated drain storms / stragglers — sampled by
  :func:`sample_fault_schedule` for training (``train --faults``) and the
  chaos evaluation matrix (``evaluate --chaos``).

Semantics (mirrored exactly by ``sim.oracle.OracleSim``):

- A node is **down** on every half-open interval
  ``[down_start, down_end)`` of its row. While down, its free GPUs are
  invisible to placement (capacity masked to zero) and any job holding
  an allocation on it is killed back to the PENDING queue at the drain
  instant — *never lost*: attained service is preserved (the sim's
  checkpointed-preemption model), and the job re-enters the queue for
  re-placement once capacity exists. Conservation (``free + allocated ==
  capacity`` per node, no job vanishing) is a tested invariant.
- A **straggler** node has ``slowdown > 1``: remaining work on it
  stretches by that factor, and a gang spanning several nodes runs at
  its *slowest* node's speed (all-or-nothing gang semantics).
- Drain starts and node returns are events: ``core.next_event_time``
  includes the next transition, so the decision loop always stops AT a
  transition and never integrates across one.

This is the *simulated cluster's* fault layer — what the learned
scheduler experiences and can learn to route around. The *training
harness's* fault layer (process kills, NaN grads, corrupt checkpoints)
is ``resilience.FaultInjector``; see README "Cluster chaos" for the
distinction.
"""
from __future__ import annotations

import dataclasses
import zlib
from typing import NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np


class FaultSchedule(NamedTuple):
    """Per-node fault trace (fixed shapes; ``W`` = drain windows per node,
    +inf padding). Rows are sorted by ``down_start`` ascending —
    :func:`validate_fault_schedule` enforces it, mirroring the submit-
    sorted contract of :class:`~.core.Trace`."""
    down_start: jax.Array  # f32[N, W] drain instants (+inf = unused slot)
    down_end: jax.Array    # f32[N, W] return instants (+inf = never)
    slowdown: jax.Array    # f32[N]    work-stretch factor (1.0 = healthy)

    @property
    def n_nodes(self) -> int:
        return int(self.down_start.shape[-2])


def no_faults(n_nodes: int, n_waves: int = 1) -> FaultSchedule:
    """The permanently-healthy schedule (host arrays) — the shape-
    compatible identity element, so clean and chaotic regimes share one
    compiled program."""
    return FaultSchedule(
        down_start=np.full((n_nodes, n_waves), np.inf, np.float32),
        down_end=np.full((n_nodes, n_waves), np.inf, np.float32),
        slowdown=np.ones((n_nodes,), np.float32))


# ---- branch-free consumption (jit/vmap-safe) --------------------------------

def node_up(faults: FaultSchedule, t: jax.Array) -> jax.Array:
    """bool[N]: node is serving at time ``t`` (down on [start, end))."""
    down = jnp.any((faults.down_start <= t) & (t < faults.down_end),
                   axis=-1)
    return ~down


def next_transition(faults: FaultSchedule, t: jax.Array) -> jax.Array:
    """Earliest drain-start or node-return strictly after ``t`` (+inf if
    none) — a fault transition is an event: state changes discontinuously
    (drain kills jobs; return restores capacity), so the decision loop
    must stop there."""
    times = jnp.stack([faults.down_start, faults.down_end])
    return jnp.min(jnp.where(times > t, times, jnp.inf))


def job_stretch(faults: FaultSchedule, alloc: jax.Array) -> jax.Array:
    """f32[J] per-job work-stretch factor: a gang runs at its SLOWEST
    node's speed (all-or-nothing gang semantics), 1.0 for jobs holding no
    allocation."""
    on = alloc > 0                                        # [J, N]
    return jnp.max(jnp.where(on, faults.slowdown[None, :], 1.0), axis=1)


def effective_free(faults: "FaultSchedule | None", free: jax.Array,
                   t: jax.Array) -> jax.Array:
    """Placement's view of the free-GPU vector: drained nodes offer
    zero capacity. ``faults=None`` is the healthy fast path (identity)."""
    if faults is None:
        return free
    return jnp.where(node_up(faults, t), free, 0)


# ---- host-side validation (fail-fast, mirrors validate_trace) ---------------

def validate_fault_schedule(n_nodes: int, faults: FaultSchedule,
                            ) -> FaultSchedule:
    """Host-side guard mirroring :func:`~.core.validate_trace`: inside the
    jitted sim a malformed schedule (end before start, unsorted windows)
    cannot raise — it surfaces as silently wrong drain masks. Raise here
    instead, fail-fast with the offending field named. Returns the
    schedule as host numpy arrays."""
    start = np.asarray(faults.down_start, np.float32)
    end = np.asarray(faults.down_end, np.float32)
    slow = np.asarray(faults.slowdown, np.float32)
    if start.ndim != 2 or start.shape != end.shape:
        raise ValueError(
            f"fault schedule wants down_start/down_end of matching shape "
            f"[n_nodes, n_waves]; got {start.shape} vs {end.shape}")
    if start.shape[0] != n_nodes or slow.shape != (n_nodes,):
        raise ValueError(
            f"fault schedule is shaped for {start.shape[0]} node(s) with "
            f"slowdown {slow.shape}; the cluster has {n_nodes}")
    finite = np.isfinite(start)
    if (start[finite] < 0).any():
        raise ValueError("drain start times must be >= 0")
    if np.isnan(start).any() or np.isnan(end).any():
        raise ValueError("fault schedule times must not be NaN")
    if (end[finite] <= start[finite]).any():
        raise ValueError(
            "drain durations must be positive (down_end > down_start "
            "for every finite drain window)")
    if (np.isfinite(end) & ~finite).any():
        raise ValueError("a node-return time without a matching drain "
                         "start (finite down_end under +inf down_start)")
    # +inf padding maps to fmax so inf-inf never produces a NaN diff and
    # padding BEFORE a finite window still reads as unsorted
    bounded = np.where(finite, start, np.finfo(np.float32).max)
    if (np.diff(bounded, axis=1) < 0).any():
        raise ValueError("per-node drain windows must be sorted by start "
                         "time (pad with +inf at the tail)")
    if (~np.isfinite(slow)).any() or (slow < 1.0).any():
        raise ValueError("slowdown factors must be finite and >= 1.0 "
                         "(1.0 = healthy; a speed-UP is not a fault)")
    return FaultSchedule(start, end, slow)


def fault_schedule_from_events(n_nodes: int, node: Sequence[int],
                               start: Sequence[float],
                               duration: Sequence[float],
                               slowdown: "Sequence[float] | None" = None,
                               n_waves: "int | None" = None,
                               ) -> FaultSchedule:
    """Pack an event list (node id, drain start, outage duration) into the
    per-node array form, validating as it goes — the trace-like ingest
    path for hand-written or externally-sourced chaos scripts."""
    node = np.asarray(node, np.int64)
    start = np.asarray(start, np.float64)
    duration = np.asarray(duration, np.float64)
    if not (node.shape == start.shape == duration.shape):
        raise ValueError("node/start/duration must have matching lengths")
    if node.size and (node.min() < 0 or node.max() >= n_nodes):
        raise ValueError(
            f"drain event node id(s) out of range [0, {n_nodes})")
    if (duration <= 0).any():
        raise ValueError("drain durations must be positive")
    if (start < 0).any():
        raise ValueError("drain start times must be >= 0")
    per_node = max((np.bincount(node, minlength=n_nodes).max()
                    if node.size else 0), 1)
    W = int(n_waves) if n_waves is not None else int(per_node)
    if per_node > W:
        raise ValueError(f"{int(per_node)} drain window(s) on one node "
                         f"exceed n_waves={W}")
    fs = no_faults(n_nodes, W)
    for n in range(n_nodes):
        mine = node == n
        order = np.argsort(start[mine], kind="stable")
        s = start[mine][order]
        fs.down_start[n, :len(s)] = s
        fs.down_end[n, :len(s)] = s + duration[mine][order]
    if slowdown is not None:
        fs = fs._replace(slowdown=np.asarray(slowdown, np.float32))
    return validate_fault_schedule(n_nodes, fs)


# ---- seeded fault regimes ---------------------------------------------------

@dataclasses.dataclass(frozen=True)
class FaultRegime:
    """A named fault DISTRIBUTION (static + hashable — it can live inside
    ``EnvParams``); :func:`sample_fault_schedule` draws concrete seeded
    :class:`FaultSchedule` data from it. Times are expressed as fractions
    of the episode horizon so one regime transfers across trace scales."""
    name: str
    p_drain: float = 0.0         # per-node chance of drain window(s)
    n_waves: int = 1             # drain windows per drained node (W)
    outage_frac: float = 0.12    # mean outage length / horizon
    storm: bool = False          # correlated starts: one instant per wave
    p_straggler: float = 0.0     # per-node chance of a slowdown factor
    slowdown_min: float = 1.5
    slowdown_max: float = 4.0


# The chaos matrix's canonical regimes (ISSUE 6): a clean control, the
# uncorrelated single-drain background rate, the correlated many-nodes-
# at-once storm (recovery-storm pressure), and pure stragglers.
FAULT_REGIMES: dict[str, FaultRegime] = {
    "none": FaultRegime("none"),
    "sporadic": FaultRegime("sporadic", p_drain=0.25),
    "storm": FaultRegime("storm", p_drain=0.6, n_waves=2,
                         outage_frac=0.08, storm=True),
    "straggler": FaultRegime("straggler", p_straggler=0.4),
}


def resolve_regime(regime: "FaultRegime | str") -> FaultRegime:
    if isinstance(regime, FaultRegime):
        return regime
    if regime not in FAULT_REGIMES:
        raise ValueError(f"unknown fault regime {regime!r}; known: "
                         f"{sorted(FAULT_REGIMES)}")
    return FAULT_REGIMES[regime]


def sample_fault_schedule(n_nodes: int, regime: "FaultRegime | str",
                          seed, horizon_s: float) -> FaultSchedule:
    """One seeded host-side draw from ``regime`` over ``[0, horizon_s)``.

    ``seed`` may be an int or a tuple of ints (e.g. ``(base_seed, env)``);
    the regime name is folded in too, so the same base seed yields
    independent draws per regime — the reproducibility tuple recorded by
    ``evaluate --chaos`` is exactly ``(seed, regime, n_nodes,
    horizon_s)``."""
    regime = resolve_regime(regime)
    if not (np.isfinite(horizon_s) and horizon_s > 0):
        raise ValueError(f"horizon_s must be finite and > 0, got "
                         f"{horizon_s}")
    entropy = list(seed) if isinstance(seed, (tuple, list)) else [int(seed)]
    rng = np.random.default_rng([zlib.crc32(regime.name.encode()),
                                 *[int(s) & 0xFFFFFFFF for s in entropy]])
    W = max(int(regime.n_waves), 1)
    fs = no_faults(n_nodes, W)
    drained = rng.random(n_nodes) < regime.p_drain
    mean_outage = max(regime.outage_frac * horizon_s, 1e-3)
    for w in range(W):
        # storms correlate: every drained node fails within a tight jitter
        # of one storm instant (recovery-storm pressure on the scheduler);
        # sporadic drains start independently anywhere in the window
        if regime.storm:
            base = rng.uniform(0.1, 0.6) * horizon_s
            starts = base + rng.exponential(0.01 * horizon_s,
                                            size=n_nodes)
        else:
            starts = rng.uniform(0.05, 0.7, size=n_nodes) * horizon_s
        outages = np.maximum(rng.exponential(mean_outage, size=n_nodes),
                             1e-3)
        fs.down_start[:, w] = np.where(drained, starts, np.inf)
        fs.down_end[:, w] = np.where(drained, starts + outages, np.inf)
    # re-sort each node's windows by start (wave draws are unordered)
    order = np.argsort(fs.down_start, axis=1, kind="stable")
    fs = FaultSchedule(np.take_along_axis(fs.down_start, order, axis=1),
                       np.take_along_axis(fs.down_end, order, axis=1),
                       fs.slowdown)
    straggler = rng.random(n_nodes) < regime.p_straggler
    fs.slowdown[:] = np.where(
        straggler,
        rng.uniform(regime.slowdown_min, regime.slowdown_max,
                    size=n_nodes), 1.0).astype(np.float32)
    return validate_fault_schedule(n_nodes, fs)


def sample_env_fault_schedules(n_nodes: int, regime: "FaultRegime | str",
                               seed: int, n_envs: int, horizon_s: float,
                               ) -> FaultSchedule:
    """Batched device schedules [E, ...] for the vec-env: env ``e`` draws
    from ``(seed, e)``, so the batch covers the regime's distribution
    rather than replaying one draw E times."""
    return stack_fault_schedules(
        [sample_fault_schedule(n_nodes, regime, (seed, e), horizon_s)
         for e in range(n_envs)])


def stack_fault_schedules(schedules: Sequence[FaultSchedule],
                          ) -> FaultSchedule:
    """Stack per-env schedules into a batched device FaultSchedule
    (leading axis E) — the fault twin of ``env.stack_traces``."""
    return jax.tree.map(lambda *xs: jnp.stack([jnp.asarray(x) for x in xs]),
                        *schedules)


def schedule_stats(faults: FaultSchedule) -> dict:
    """Host summary of one (or a batched) schedule — what the chaos
    matrix's ``env_fault`` events carry so ``obs.report`` can tell the
    story without re-deriving it from arrays."""
    start = np.asarray(faults.down_start, np.float64)
    end = np.asarray(faults.down_end, np.float64)
    slow = np.asarray(faults.slowdown, np.float64)
    finite = np.isfinite(start)
    bounded = finite & np.isfinite(end)
    return {
        "n_drains": int(finite.sum()),
        "n_permanent": int((finite & ~np.isfinite(end)).sum()),
        "total_downtime_s": float((end[bounded] - start[bounded]).sum()),
        "n_stragglers": int((slow > 1.0).sum()),
        "max_slowdown": float(slow.max()) if slow.size else 1.0,
    }


def fault_horizon(windows) -> float:
    """Rough sim-time span of a window set — the interval fault windows
    should land inside so drains actually intersect live episodes. Spans
    the arrival process plus a few mean service times of drain tail."""
    t = 0.0
    for w in windows:
        valid = np.asarray(w.valid)
        if not valid.any():
            continue
        submit = np.asarray(w.submit, np.float64)[valid]
        duration = np.asarray(w.duration, np.float64)[valid]
        t = max(t, float(submit.max()) + 4.0 * float(duration.mean()))
    return max(t, 1.0)
