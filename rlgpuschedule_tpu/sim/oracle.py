"""Exact event-driven oracle simulator (L1) — the executable spec.

Capability parity: SURVEY.md §2 "Cluster model", "Event-driven sim engine",
"Gang scheduler mechanics", "Preemption support". This is the slow, obviously-
correct Python implementation of the cluster semantics. It exists for three
reasons (SURVEY.md §7 step 2):

1. It IS the specification: the jit/vmap JAX simulator (``sim.core``) is
   property-tested to produce bit-identical schedules against this oracle.
2. Baseline schedulers (FIFO/SJF/SRTF/Tiresias, ``sim.schedulers``) run on it
   to produce the JCT comparison tables.
3. Full-trace evaluation (hundreds of thousands of jobs) runs here on host
   CPU, where a priority queue beats a fixed-shape scan.

Shared semantics (must match ``sim.core`` exactly):

- Cluster: ``n_nodes`` × ``gpus_per_node`` interchangeable GPUs; jobs may span
  nodes; gang all-or-nothing: a job runs only with its full GPU demand.
- Job lifecycle: NOT_ARRIVED → PENDING (clock ≥ submit) → RUNNING → DONE.
  Preemption: RUNNING → PENDING, attained service preserved (no restart-from-
  scratch penalty; matches Tiresias' model of checkpointed preemption).
- Placement is deterministic given the free-GPU vector:
  * PACK:   nodes sorted by (free desc, node id asc); fill greedily.
  * SPREAD: water-filling — smallest level t with Σ min(free_i, t) ≥ demand;
    alloc_i = min(free_i, t); excess trimmed from the highest node ids whose
    allocation equals t.
- Time advances only between decision points, to the next event:
  min(next arrival, next completion). Completions are processed before
  arrivals at the same instant.
- JCT(j) = finish(j) − submit(j).
"""
from __future__ import annotations

import numpy as np

from ..traces.records import ArrayTrace, JobRecord

NOT_ARRIVED, PENDING, RUNNING, DONE = 0, 1, 2, 3
PACK, SPREAD = 0, 1


def pack_placement(free: np.ndarray, demand: int) -> np.ndarray | None:
    """Fill the freest nodes first; ties broken by lowest node id."""
    if demand > int(free.sum()):
        return None
    order = np.lexsort((np.arange(len(free)), -free))  # free desc, id asc
    alloc = np.zeros_like(free)
    left = demand
    for n in order:
        take = min(int(free[n]), left)
        alloc[n] = take
        left -= take
        if left == 0:
            break
    return alloc


def spread_placement(free: np.ndarray, demand: int) -> np.ndarray | None:
    """Water-filling: balance the allocation as evenly as the free vector
    allows. Excess (when Σ min(free, t) overshoots) is trimmed from the
    highest node ids among nodes allocated exactly t."""
    if demand > int(free.sum()):
        return None
    t = 0
    while int(np.minimum(free, t).sum()) < demand:
        t += 1
    alloc = np.minimum(free, t).astype(free.dtype)
    excess = int(alloc.sum()) - demand
    if excess > 0:
        at_t = [n for n in range(len(free)) if alloc[n] == t]
        for n in sorted(at_t, reverse=True)[:excess]:
            alloc[n] -= 1
    return alloc


class OracleSim:
    """Exact discrete-event simulation of one cluster over one trace.

    ``faults`` (a :class:`~.faults.FaultSchedule`, validated at
    construction) attaches the cluster fault process — the same
    semantics the jitted ``sim.core`` implements branch-free (and is
    property-tested against): drained nodes offer zero placement
    capacity and kill their running jobs back to PENDING with attained
    service preserved; straggler nodes stretch remaining work; drain
    starts and node returns are events."""

    def __init__(self, trace: ArrayTrace | list[JobRecord], n_nodes: int,
                 gpus_per_node: int, faults=None):
        if isinstance(trace, list):
            from ..traces.records import to_array_trace
            trace = to_array_trace(trace)
        self.trace = trace
        self.n_nodes = n_nodes
        self.gpus_per_node = gpus_per_node
        # a DomainSchedule in the faults slot carries per-node capacity
        # (geometry randomization); extract it BEFORE validation, which
        # normalizes down to the plain 3-field fault triple
        cap = getattr(faults, "capacity", None)
        self.node_capacity = (np.full(n_nodes, gpus_per_node, np.int32)
                              if cap is None
                              else np.asarray(cap, np.int32).copy())
        if self.node_capacity.shape != (n_nodes,):
            raise ValueError(
                f"domain capacity must have shape ({n_nodes},); got "
                f"{self.node_capacity.shape}")
        self.capacity = int(self.node_capacity.sum())
        if trace.num_jobs and int(trace.gpus[trace.valid].max()) > self.capacity:
            raise ValueError("a job demands more GPUs than the cluster has")
        self.faults = None
        if faults is not None:
            from .faults import validate_fault_schedule
            self.faults = validate_fault_schedule(n_nodes, faults)
        self.reset()

    def reset(self):
        J = self.trace.max_jobs
        self.clock = 0.0
        self.status = np.where(self.trace.valid, NOT_ARRIVED, DONE).astype(np.int32)
        self.remaining = self.trace.duration.astype(np.float64).copy()
        self.start = np.full(J, np.nan)
        self.finish = np.full(J, np.nan)
        self.alloc = np.zeros((J, self.n_nodes), np.int32)
        self.free = self.node_capacity.copy()
        self._process_arrivals()
        return self

    # ---- events ------------------------------------------------------------

    def _process_arrivals(self):
        arrived = (self.status == NOT_ARRIVED) & (self.trace.submit <= self.clock)
        self.status[arrived] = PENDING

    def node_up(self, t: float | None = None) -> np.ndarray:
        """bool[N]: nodes serving at ``t`` (down on [start, end))."""
        if self.faults is None:
            return np.ones(self.n_nodes, bool)
        t = self.clock if t is None else t
        f = self.faults
        return ~((np.asarray(f.down_start) <= t)
                 & (t < np.asarray(f.down_end))).any(axis=1)

    def effective_free(self) -> np.ndarray:
        """Placement's view of free GPUs: drained nodes offer zero."""
        if self.faults is None:
            return self.free
        return np.where(self.node_up(), self.free, 0).astype(self.free.dtype)

    def _stretch(self) -> np.ndarray:
        """f64[J] per-job work-stretch: a gang runs at its slowest node's
        speed; 1.0 with no faults or no allocation."""
        if self.faults is None:
            return np.ones(self.trace.max_jobs)
        slow = np.asarray(self.faults.slowdown, np.float64)
        return np.where(self.alloc > 0, slow[None, :], 1.0).max(axis=1)

    def next_event_time(self) -> float:
        """Earliest future arrival, completion, or fault transition; +inf
        if none exists."""
        t = np.inf
        na = self.status == NOT_ARRIVED
        if na.any():
            t = min(t, float(self.trace.submit[na].min()))
        run = self.status == RUNNING
        if run.any():
            eta = self.remaining[run] * self._stretch()[run]
            t = min(t, self.clock + float(eta.min()))
        if self.faults is not None:
            times = np.concatenate([
                np.asarray(self.faults.down_start, np.float64).ravel(),
                np.asarray(self.faults.down_end, np.float64).ravel()])
            future = times[times > self.clock]
            if future.size:
                t = min(t, float(future.min()))
        return t

    def advance_to(self, t: float) -> float:
        """Advance the clock to ``t`` (≤ next event time; schedulers may pass
        an earlier timer wake, e.g. a Tiresias demotion instant). Completions
        falling exactly on ``t`` are processed before arrivals; drain kills
        (jobs on nodes down at ``t`` back to PENDING, service preserved)
        land between the two, matching ``sim.core.advance_to``. Returns dt."""
        if not np.isfinite(t):
            return 0.0
        if t > self.next_event_time() + 1e-9:
            raise ValueError("advance_to would skip over an event")
        dt = t - self.clock
        run = self.status == RUNNING
        self.remaining[run] -= dt / self._stretch()[run]
        self.clock = t
        completed = run & (self.remaining <= 1e-9)
        for j in np.flatnonzero(completed):
            self.status[j] = DONE
            self.finish[j] = t
            self.remaining[j] = 0.0
            self.free += self.alloc[j]
            self.alloc[j] = 0
        if self.faults is not None:
            down = ~self.node_up()
            killed = (self.status == RUNNING) & \
                ((self.alloc > 0) & down[None, :]).any(axis=1)
            for j in np.flatnonzero(killed):
                self.free += self.alloc[j]
                self.alloc[j] = 0
                self.status[j] = PENDING
        self._process_arrivals()
        return dt

    def advance_to_next_event(self) -> float:
        """Advance the clock to the next event; returns dt (0 if no event)."""
        return self.advance_to(self.next_event_time())

    # ---- scheduling actions ------------------------------------------------

    def try_place(self, j: int, mode: int = PACK) -> bool:
        """Gang-place pending job j; returns False if infeasible/not
        pending. Placement sees drained nodes as zero free capacity, so a
        gang can never land on a down node."""
        if self.status[j] != PENDING:
            return False
        demand = int(self.trace.gpus[j])
        place = (pack_placement if mode == PACK
                 else spread_placement)(self.effective_free(), demand)
        if place is None:
            return False
        self.alloc[j] = place
        self.free -= place
        self.status[j] = RUNNING
        if np.isnan(self.start[j]):
            self.start[j] = self.clock
        return True

    def preempt(self, j: int) -> bool:
        if self.status[j] != RUNNING:
            return False
        self.free += self.alloc[j]
        self.alloc[j] = 0
        self.status[j] = PENDING
        return True

    def rl_step(self, action: int, queue_len: int, n_placements: int = 1,
                n_preempt: int = 0) -> dict:
        """One RL decision-point step — the reference semantics that the
        jitted ``sim.core.rl_step`` must reproduce exactly (SURVEY.md §3.2).

        Action layout ``[K*P placements][R preemptions][no-op]``:
        ``action < K*P`` places slot ``action // n_placements`` of the
        pending queue with mode ``action % n_placements`` (0=pack,
        1=spread); ``K*P <= action < K*P + n_preempt`` preempts slot
        ``action - K*P`` of the running queue (most attained GPU-service
        first — the Tiresias demotion order); anything else is no-op.

        Semantics: a successful placement or preemption costs no simulated
        time (the agent acts again at the same instant). A no-op / invalid
        / infeasible action advances the clock to the next event. If no
        future event exists (nothing running ⇒ cluster fully free) the
        head-of-queue job is force-placed to guarantee progress — it is
        always feasible because per-job demand ≤ capacity is enforced at
        construction.
        """
        n_place = queue_len * n_placements
        queue = self.pending_jobs()[:queue_len]
        placed = preempted = first_placed = False
        if action < n_place:
            k, p = divmod(action, n_placements)
            if k < len(queue):
                first = bool(np.isnan(self.start[queue[k]]))
                placed = self.try_place(queue[k], p)
                first_placed = placed and first
        elif action < n_place + n_preempt:
            run_q = self.running_queue(n_preempt)
            r = action - n_place
            if r < len(run_q):
                preempted = self.preempt(run_q[r])
        dt, n_before = 0.0, self.in_system()
        if not (placed or preempted):
            t = self.next_event_time()
            if np.isfinite(t):
                dt = self.advance_to(t)
            elif queue:
                # may legitimately fail under faults: an exhausted event
                # horizon with a permanently-drained node can leave the
                # head job larger than the surviving capacity (matches
                # sim.core's forced_ok=False path — the episode then only
                # ends via the env horizon)
                first = bool(np.isnan(self.start[queue[0]]))
                placed = self.try_place(queue[0], PACK)
                first_placed = placed and first
        return {"placed": placed, "dt": dt, "in_system_before": n_before,
                "done": self.done(), "preempted": preempted,
                "first_placed": first_placed}

    # ---- queries -----------------------------------------------------------

    def pending_jobs(self) -> list[int]:
        """Pending job ids ordered by (submit asc, id asc) — the queue order
        the RL action space indexes into."""
        pend = np.flatnonzero(self.status == PENDING)
        return sorted(pend, key=lambda j: (self.trace.submit[j], j))

    def running_jobs(self) -> list[int]:
        return list(np.flatnonzero(self.status == RUNNING))

    def running_queue(self, n_preempt: int) -> list[int]:
        """Running job ids ordered by attained GPU-service DESC (ties → id
        asc) — the slots the preemptive action space indexes into (matches
        ``sim.core.running_queue``)."""
        return sorted(self.running_jobs(),
                      key=lambda j: (-self.attained_service(j), j)
                      )[:n_preempt]

    def in_system(self) -> int:
        return int(((self.status == PENDING) | (self.status == RUNNING)).sum())

    def done(self) -> bool:
        return bool((self.status[self.trace.valid] == DONE).all())

    def attained_service(self, j: int) -> float:
        """GPU-seconds of service attained (Tiresias' priority key)."""
        executed = float(self.trace.duration[j]) - float(self.remaining[j])
        return executed * float(self.trace.gpus[j])

    def jcts(self) -> np.ndarray:
        v = self.trace.valid & (self.status == DONE)
        return (self.finish[v] - self.trace.submit[v]).astype(np.float64)

    def avg_jct(self) -> float:
        j = self.jcts()
        return float(j.mean()) if len(j) else float("nan")

    def utilization(self) -> float:
        """Fraction of GPUs currently busy."""
        return 1.0 - float(self.free.sum()) / self.capacity

    def gpus_consistent(self) -> bool:
        """Conservation invariant: allocated + free == capacity, per node."""
        used = self.alloc.sum(axis=0)
        return bool((used + self.free == self.node_capacity).all())
