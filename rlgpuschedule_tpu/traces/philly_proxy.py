"""Philly-statistics proxy trace generator (L0).

Capability parity: SURVEY.md §2 "Philly trace loader" / §7 hard part (b)
"faithful Philly-trace semantics". The real Microsoft Philly CSV cannot
exist on this machine (no network — SURVEY.md top caveat), so config 2's
512-GPU runs need a *reproducible stand-in with the published Philly
workload statistics* (VERDICT r2 missing #3 / next-round #3). This
generator is seeded and matches the distributions reported in Jeon et al.,
"Analysis of Large-Scale Multi-Tenant GPU Clusters for DNN Training
Workloads" (USENIX ATC'19), the paper the Philly trace release accompanies:

- **Gang sizes**: single-GPU jobs dominate by count; demand is power-of-two
  up to 128 GPUs with a thin large-job tail.
- **Durations**: heavy-tailed — minutes-scale median, hours-scale mean,
  multi-day maximum (log-normal body, sigma ~2).
- **Terminal status mix**: roughly 2/3 passed, ~1/4 killed, ~1/9 failed;
  failed jobs die early (short durations), killed jobs skew long — and
  unsuccessful jobs still occupy their GPUs for their whole runtime, which
  is why they must stay in the trace (records.py STATUS_* note).
- **Arrivals**: Poisson modulated by a diurnal cycle (busy day, quiet
  night) — not a flat rate.
- **Tenants**: ~14 virtual clusters with a skewed (Zipf-like) job share.

Rather than fixing an arrival rate, the generator targets an *offered
load* (requested GPU-seconds per wall-second / cluster GPUs) so the same
statistics stress a 512-GPU simulated cluster (config 2) the way the real
trace stressed Philly's ~2.5k GPUs. Philly ran hot (queueing was the
norm), so the default load is 1.1 — slightly oversubscribed, which is the
regime where scheduling policy matters.
"""
from __future__ import annotations

import math
from typing import Sequence

import numpy as np

from .records import (ArrayTrace, JobRecord, STATUS_FAILED, STATUS_KILLED,
                      STATUS_PASS, to_array_trace)

# Gang-size mix by job count (power-of-two, 1-GPU heavy, thin 128 tail).
PHILLY_GPU_SIZES = (1, 2, 4, 8, 16, 32, 64, 128)
PHILLY_GPU_PROBS = (0.70, 0.09, 0.08, 0.08, 0.03, 0.013, 0.005, 0.002)

# Terminal-status mix by job count.
PHILLY_STATUS = (STATUS_PASS, STATUS_KILLED, STATUS_FAILED)
PHILLY_STATUS_PROBS = (0.66, 0.22, 0.12)
# Duration multiplier per status: failed jobs fail early; killed jobs are
# the long-runners users eventually give up on.
_STATUS_DUR_MULT = {STATUS_PASS: 1.0, STATUS_KILLED: 2.0, STATUS_FAILED: 0.25}

# Log-normal duration body: median ~12 min, sigma 1.9 => mean ~ 4.4 h,
# heavy tail clamped to 30 days; floor 30 s.
PHILLY_MEDIAN_DURATION_S = 720.0
PHILLY_DURATION_SIGMA = 1.9
MIN_DURATION_S = 30.0
MAX_DURATION_S = 30 * 86400.0

N_VIRTUAL_CLUSTERS = 14
_DAY_S = 86400.0
_HOUR_S = 3600.0

# Hour-of-day arrival-rate multipliers (mean 1.0), the piecewise
# replacement for the old +-50% sinusoid: Philly's published diurnal
# pattern (Jeon et al. ATC'19 §3.1) is NOT sinusoidal — submissions
# climb through the morning, plateau high across working hours with a
# visible lunch dip, stay elevated into the evening (researchers queue
# jobs before leaving), and trough pre-dawn. 24 bins, trough ~0.5x at
# 04-05h, peak ~1.5x mid-afternoon.
PHILLY_HOURLY: tuple[float, ...] = (
    0.72, 0.62, 0.55, 0.51, 0.48, 0.50,   # 00-05: overnight trough
    0.58, 0.74, 0.95, 1.18, 1.35, 1.42,   # 06-11: morning ramp
    1.30, 1.38, 1.48, 1.50, 1.45, 1.38,   # 12-17: working-hour plateau
    1.25, 1.12, 0.97, 0.90, 0.88, 0.79,   # 18-23: evening tail-off
)
assert abs(sum(PHILLY_HOURLY) / 24.0 - 1.0) < 1e-6, \
    "PHILLY_HOURLY must average 1.0 so `rate` stays the mean rate"


def _diurnal_arrivals(rate: float, n_jobs: int,
                      rng: np.random.Generator,
                      hourly: "Sequence[float]" = PHILLY_HOURLY,
                      ) -> np.ndarray:
    """Non-homogeneous Poisson arrivals at mean rate ``rate`` modulated
    by a piecewise-constant hour-of-day curve, by thinning: candidates
    at the peak rate ``rate * max(hourly)``, each kept with probability
    ``rate(t)/peak`` where ``rate(t)`` reads the candidate's hour-of-day
    bin. ``hourly`` is relative multipliers (mean ~1.0 keeps ``rate``
    the mean rate); seeded entirely through ``rng``."""
    curve = np.asarray(hourly, np.float64)
    if curve.ndim != 1 or curve.size != 24:
        raise ValueError(f"hourly curve must have 24 bins, got "
                         f"{curve.shape}")
    if not np.all(np.isfinite(curve)) or curve.min() < 0 \
            or curve.max() <= 0:
        raise ValueError("hourly curve must be finite, non-negative, "
                         "with positive peak")
    peak_mult = float(curve.max())
    peak = rate * peak_mult
    out = np.empty(0, np.float64)
    t = 0.0
    while out.size < n_jobs:
        need = n_jobs - out.size
        # oversample so one round usually suffices
        n_cand = int(need * peak_mult * 1.2) + 16
        cand = t + np.cumsum(rng.exponential(1.0 / peak, size=n_cand))
        t = float(cand[-1])
        hour = ((cand % _DAY_S) // _HOUR_S).astype(np.int64)
        accept = curve[hour] / peak_mult
        out = np.concatenate([out, cand[rng.random(n_cand) < accept]])
    return out[:n_jobs]


def _mean_gpus(sizes: Sequence[int], probs: Sequence[float]) -> float:
    return float(np.dot(sizes, np.asarray(probs) / np.sum(probs)))


def base_arrival_rate(n_gpus: int, load: float,
                      gpu_sizes: Sequence[int] = PHILLY_GPU_SIZES,
                      gpu_probs: Sequence[float] = PHILLY_GPU_PROBS,
                      median_duration: float = PHILLY_MEDIAN_DURATION_S,
                      sigma: float = PHILLY_DURATION_SIGMA) -> float:
    """Jobs/sec such that offered load (requested GPU-seconds per second /
    n_gpus) equals ``load``: rate = load * n_gpus / E[gpus * duration]
    (gang size and duration are drawn independently). The duration mean is
    the analytic status-mixed log-normal mean; the 30-day clamp's effect
    (well under 2% of mass) is ignored."""
    body_mean = math.exp(math.log(median_duration) + 0.5 * sigma ** 2)
    mean_dur = body_mean * sum(p * _STATUS_DUR_MULT[s] for s, p in
                               zip(PHILLY_STATUS, PHILLY_STATUS_PROBS))
    return load * n_gpus / (_mean_gpus(gpu_sizes, gpu_probs) * mean_dur)


def gen_philly_proxy_jobs(
    n_jobs: int,
    seed: int,
    n_gpus: int = 512,
    load: float = 1.1,
    max_gang: int | None = None,
    n_tenants: int = N_VIRTUAL_CLUSTERS,
    gpu_sizes: Sequence[int] = PHILLY_GPU_SIZES,
    gpu_probs: Sequence[float] = PHILLY_GPU_PROBS,
    median_duration: float = PHILLY_MEDIAN_DURATION_S,
    sigma: float = PHILLY_DURATION_SIGMA,
) -> list[JobRecord]:
    """``n_jobs`` seeded jobs with Philly-statistics marginals, offered at
    ``load``× the capacity of an ``n_gpus`` cluster. ``max_gang`` drops
    gang sizes above the cluster's reach (e.g. 8 for a single
    8-GPU-per-node pod with pack-only placement) by renormalizing the size
    mix — demand clamping at upload would otherwise distort the mix."""
    if n_jobs <= 0:
        raise ValueError("n_jobs must be positive")
    rng = np.random.default_rng(seed)

    sizes = np.asarray(gpu_sizes, np.int64)
    probs = np.asarray(gpu_probs, np.float64)
    if max_gang is not None:
        keep = sizes <= max_gang
        if not keep.any():
            raise ValueError(f"max_gang={max_gang} below smallest gang size")
        sizes, probs = sizes[keep], probs[keep]
    probs = probs / probs.sum()

    rate = base_arrival_rate(n_gpus, load, sizes, probs, median_duration,
                             sigma)
    submit = _diurnal_arrivals(rate, n_jobs, rng)
    submit -= submit[0]          # first job at t=0, matching gen_poisson_jobs

    gpus = rng.choice(sizes, size=n_jobs, p=probs)
    status = rng.choice(np.asarray(PHILLY_STATUS, np.int64), size=n_jobs,
                        p=np.asarray(PHILLY_STATUS_PROBS))
    mult = np.asarray([_STATUS_DUR_MULT[s] for s in PHILLY_STATUS])[status]
    dur = rng.lognormal(math.log(median_duration), sigma, size=n_jobs) * mult
    dur = np.clip(dur, MIN_DURATION_S, MAX_DURATION_S)

    # Zipf-skewed virtual-cluster share (tenant 0 busiest), like Philly's
    # uneven 14 VCs.
    ranks = np.arange(1, n_tenants + 1, dtype=np.float64)
    tenant_probs = (1.0 / ranks) / np.sum(1.0 / ranks)
    tenant = rng.choice(n_tenants, size=n_jobs, p=tenant_probs)

    return [JobRecord(i, float(submit[i]), float(dur[i]), int(gpus[i]),
                      int(tenant[i]), int(status[i]))
            for i in range(n_jobs)]


def gen_philly_proxy_trace(n_jobs: int, seed: int,
                           max_jobs: int | None = None,
                           **kw) -> ArrayTrace:
    return to_array_trace(gen_philly_proxy_jobs(n_jobs, seed, **kw),
                          max_jobs=max_jobs)


# ---- Alibaba-PAI-statistics preset ------------------------------------------
# Config 3's multi-tenant fairness runs need the same no-CSV stand-in for
# the PAI trace (Weng et al., "MLaaS in the Wild", NSDI'22): tasks are much
# smaller than Philly's (1-GPU dominates even harder, gangs rarely exceed
# 8), durations shorter (minutes-scale median), and tenancy is the point —
# many users sharing one cluster.

PAI_GPU_SIZES = (1, 2, 4, 8)
PAI_GPU_PROBS = (0.81, 0.10, 0.06, 0.03)
PAI_MEDIAN_DURATION_S = 300.0
PAI_DURATION_SIGMA = 1.6
PAI_N_TENANTS = 24


def gen_pai_proxy_jobs(n_jobs: int, seed: int, n_gpus: int = 128,
                       load: float = 1.1, max_gang: int | None = None,
                       n_tenants: int = PAI_N_TENANTS) -> list[JobRecord]:
    return gen_philly_proxy_jobs(
        n_jobs, seed, n_gpus=n_gpus, load=load, max_gang=max_gang,
        n_tenants=n_tenants, gpu_sizes=PAI_GPU_SIZES,
        gpu_probs=PAI_GPU_PROBS, median_duration=PAI_MEDIAN_DURATION_S,
        sigma=PAI_DURATION_SIGMA)


def gen_pai_proxy_trace(n_jobs: int, seed: int, max_jobs: int | None = None,
                        **kw) -> ArrayTrace:
    return to_array_trace(gen_pai_proxy_jobs(n_jobs, seed, **kw),
                          max_jobs=max_jobs)
