"""Alibaba PAI cluster-trace loader (L0).

Capability parity: SURVEY.md §2 "Alibaba PAI trace loader" and §0 config 3
(A2C multi-tenant fairness on PAI). The public Alibaba cluster-trace-gpu
releases record per-instance/task rows with gpu requests (``plan_gpu`` as a
percentage, 100 = one full GPU), start/end times, and a user id. This loader
accepts that CSV shape (one row per job/instance), rounds fractional GPU
requests up to whole gang sizes (our simulator allocates whole GPUs), and maps
users to dense tenant ids for the fairness reward.

Expected columns (aliases): job_name (job_id, inst_id), submit_time
(create_time), start_time, end_time, plan_gpu (gpu_request, num_gpus), user
(user_name, group).
"""
from __future__ import annotations

import csv
import math
from pathlib import Path

from .records import JobRecord, ArrayTrace, parse_status, to_array_trace

_ALIASES = {
    "job_id": ("job_name", "job_id", "inst_id", "instance"),
    "submit": ("submit_time", "create_time", "submit"),
    "start": ("start_time", "start"),
    "end": ("end_time", "end"),
    "gpus": ("plan_gpu", "gpu_request", "num_gpus", "gpus"),
    "status": ("status", "state"),
    "tenant": ("user", "user_name", "group", "tenant"),
}


def _col(header, key):
    lower = {h.lower().strip(): h for h in header}
    for alias in _ALIASES[key]:
        if alias in lower:
            return lower[alias]
    return None


def load_pai_jobs(path: str | Path, max_jobs: int | None = None,
                  gpu_is_percent: bool | None = None) -> list[JobRecord]:
    """Parse a PAI-style CSV. ``gpu_is_percent=None`` auto-detects: if the
    column is named plan_gpu or any value exceeds 8, values are percentages
    of a GPU (PAI convention) and are divided by 100 before ceiling."""
    path = Path(path)
    with path.open(newline="") as f:
        reader = csv.DictReader(f)
        header = reader.fieldnames or []
        cols = {k: _col(header, k) for k in _ALIASES}
        for need in ("submit", "gpus", "start", "end"):
            if cols[need] is None and not (need == "submit" and cols["start"]):
                raise ValueError(f"{path}: missing column for {need}; got {header}")
        rows = []
        for row in reader:
            if max_jobs is not None and len(rows) >= max_jobs:
                break
            try:
                start = float(row[cols["start"]])
                end = float(row[cols["end"]])
                submit = float(row[cols["submit"]]) if cols["submit"] else start
                gpu_raw = float(row[cols["gpus"]])
            except (ValueError, KeyError, TypeError):
                continue
            duration = end - start
            if duration <= 0 or gpu_raw <= 0:
                continue
            status = parse_status(row[cols["status"]]) if cols["status"] else 0
            tkey = row[cols["tenant"]].strip() if cols["tenant"] else "0"
            rows.append((submit, duration, gpu_raw, tkey, status))
    if not rows:
        return []
    if gpu_is_percent is None:
        gpu_is_percent = (cols["gpus"].lower() == "plan_gpu"
                          or any(r[2] > 8 for r in rows))
    t0 = min(r[0] for r in rows)
    rows.sort(key=lambda r: r[0])
    tenants: dict[str, int] = {}
    jobs = []
    for i, (s, d, g, tkey, st) in enumerate(rows):
        gpus = max(1, math.ceil(g / 100.0 if gpu_is_percent else g))
        jobs.append(JobRecord(i, s - t0, d, gpus,
                              tenants.setdefault(tkey, len(tenants)), st))
    return jobs


def load_pai(path: str | Path, max_jobs: int | None = None) -> ArrayTrace:
    return to_array_trace(load_pai_jobs(path, max_jobs=max_jobs),
                          max_jobs=max_jobs)
