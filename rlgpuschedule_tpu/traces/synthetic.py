"""Seeded synthetic Poisson job-trace generator (L0).

Capability parity: SURVEY.md §2 "Synthetic trace generator" and §0 config 1
("64-GPU synthetic Poisson job trace"). Poisson arrivals, log-normal service
times, power-of-two gang sizes — the standard shape of GPU-cluster workloads
(small jobs dominate, durations heavy-tailed).
"""
from __future__ import annotations

from typing import Sequence

import numpy as np

from .records import JobRecord, ArrayTrace, to_array_trace

DEFAULT_GPU_SIZES = (1, 2, 4, 8)
DEFAULT_GPU_PROBS = (0.55, 0.2, 0.15, 0.1)


def gen_poisson_jobs(
    rate: float,
    n_jobs: int,
    seed: int,
    mean_duration: float = 600.0,
    sigma: float = 1.0,
    gpu_sizes: Sequence[int] = DEFAULT_GPU_SIZES,
    gpu_probs: Sequence[float] = DEFAULT_GPU_PROBS,
    n_tenants: int = 1,
) -> list[JobRecord]:
    """Poisson arrivals at ``rate`` jobs/sec; log-normal durations with the
    given mean; gang sizes drawn from ``gpu_sizes``. Fully determined by
    ``seed``."""
    if rate <= 0 or n_jobs <= 0:
        raise ValueError("rate and n_jobs must be positive")
    rng = np.random.default_rng(seed)
    inter = rng.exponential(1.0 / rate, size=n_jobs)
    submit = np.cumsum(inter)
    submit[0] = 0.0  # first job arrives at t=0 so episodes start immediately
    # log-normal with mean = mean_duration: mu = ln(mean) - sigma^2/2
    mu = np.log(mean_duration) - 0.5 * sigma**2
    duration = np.maximum(1.0, rng.lognormal(mu, sigma, size=n_jobs))
    gpus = rng.choice(np.asarray(gpu_sizes, np.int32), size=n_jobs,
                      p=np.asarray(gpu_probs) / np.sum(gpu_probs))
    tenant = rng.integers(0, n_tenants, size=n_jobs)
    return [JobRecord(i, float(submit[i]), float(duration[i]), int(gpus[i]),
                      int(tenant[i])) for i in range(n_jobs)]


def gen_poisson_trace(rate: float, n_jobs: int, seed: int,
                      max_jobs: int | None = None, **kw) -> ArrayTrace:
    return to_array_trace(gen_poisson_jobs(rate, n_jobs, seed, **kw),
                          max_jobs=max_jobs)
