"""Microsoft Philly cluster-trace loader (L0).

Capability parity: SURVEY.md §2 "Philly trace loader". The public MSR
philly-traces release ships job logs with per-job submit/start/end timestamps,
GPU counts, and a terminal status in {Pass, Killed, Failed}. This loader
accepts the flattened CSV form of that data (one row per job) and normalizes
it into :class:`JobRecord`s; column aliases cover the common exports. Failed
and killed jobs are kept — they occupied GPUs for their recorded runtime and
dropping them would skew JCT and utilization (SURVEY.md §5).

Expected columns (aliases in parentheses):
  job_id (jobid), submit_time (submitted_time), duration (run_time) OR
  start_time+end_time, num_gpus (gpus, gpu_num), status, user (vc, tenant).
Timestamps may be epoch seconds or ISO strings; durations are seconds.
"""
from __future__ import annotations

import csv
import datetime as _dt
from pathlib import Path

from .records import JobRecord, ArrayTrace, parse_status, to_array_trace

_ALIASES = {
    "job_id": ("job_id", "jobid", "job"),
    "submit": ("submit_time", "submitted_time", "submit"),
    "start": ("start_time", "start"),
    "end": ("end_time", "finish_time", "end"),
    "duration": ("duration", "run_time", "runtime"),
    "gpus": ("num_gpus", "gpus", "gpu_num", "gpu_count"),
    "status": ("status", "state", "final_status"),
    "tenant": ("user", "vc", "tenant", "virtual_cluster"),
}


def _col(header: list[str], key: str) -> str | None:
    lower = {h.lower().strip(): h for h in header}
    for alias in _ALIASES[key]:
        if alias in lower:
            return lower[alias]
    return None


def _to_seconds(v: str) -> float:
    v = v.strip()
    try:
        return float(v)
    except ValueError:
        return _dt.datetime.fromisoformat(v).timestamp()


def load_philly_jobs(path: str | Path, max_jobs: int | None = None,
                     min_duration: float = 1.0) -> list[JobRecord]:
    """Parse a Philly-style job CSV into normalized records.

    Jobs with no resolvable duration or zero GPUs are skipped (Philly contains
    never-scheduled entries). Submit times are re-based to the earliest job.
    Tenants (users/VCs) are mapped to dense integer ids.
    """
    path = Path(path)
    with path.open(newline="") as f:
        reader = csv.DictReader(f)
        header = reader.fieldnames or []
        cols = {k: _col(header, k) for k in _ALIASES}
        if cols["submit"] is None or cols["gpus"] is None:
            raise ValueError(f"{path}: need submit_time and num_gpus columns; got {header}")
        if cols["duration"] is None and (cols["start"] is None or cols["end"] is None):
            raise ValueError(f"{path}: need duration or start+end columns")
        tenants: dict[str, int] = {}
        raw = []
        for i, row in enumerate(reader):
            if max_jobs is not None and len(raw) >= max_jobs:
                break
            try:
                submit = _to_seconds(row[cols["submit"]])
                gpus = int(float(row[cols["gpus"]]))
                if cols["duration"] is not None and row[cols["duration"]].strip():
                    duration = float(row[cols["duration"]])
                else:
                    duration = _to_seconds(row[cols["end"]]) - _to_seconds(row[cols["start"]])
            except (ValueError, KeyError, TypeError):
                continue
            if gpus <= 0 or duration < min_duration:
                continue
            status = parse_status(row[cols["status"]]) if cols["status"] else 0
            tkey = row[cols["tenant"]].strip() if cols["tenant"] else "0"
            tenant = tenants.setdefault(tkey, len(tenants))
            raw.append((submit, duration, gpus, tenant, status))
    if not raw:
        return []
    t0 = min(r[0] for r in raw)
    raw.sort(key=lambda r: r[0])
    return [JobRecord(i, s - t0, d, g, t, st)
            for i, (s, d, g, t, st) in enumerate(raw)]


def load_philly(path: str | Path, max_jobs: int | None = None) -> ArrayTrace:
    jobs = load_philly_jobs(path, max_jobs=max_jobs)
    return to_array_trace(jobs, max_jobs=max_jobs)
