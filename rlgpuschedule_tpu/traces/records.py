"""Job records and fixed-shape array traces (L0).

Capability parity: SURVEY.md §2 rows "Philly trace loader" / "Alibaba PAI
trace loader" / "Synthetic trace generator" — a common job record normalizing
heterogeneous trace schemas (submit time, GPU demand, duration, tenant,
terminal status), plus a padded fixed-shape array form because the jitted
simulator needs static shapes (SURVEY.md §7 step 1).
"""
from __future__ import annotations

import dataclasses
from typing import Sequence

import numpy as np

# Terminal status of a job in the source trace. Failed/killed jobs still
# consume cluster resources for their recorded duration (Philly contains many
# such jobs and dropping them skews JCT; SURVEY.md §5 "failure detection").
STATUS_PASS = 0
STATUS_KILLED = 1
STATUS_FAILED = 2

_STATUS_NAMES = {"pass": STATUS_PASS, "passed": STATUS_PASS,
                 "completed": STATUS_PASS, "terminated": STATUS_PASS,
                 "killed": STATUS_KILLED, "canceled": STATUS_KILLED,
                 "cancelled": STATUS_KILLED,
                 "failed": STATUS_FAILED, "error": STATUS_FAILED}


def parse_status(s: str | int) -> int:
    if isinstance(s, (int, np.integer)):
        return int(s)
    return _STATUS_NAMES.get(s.strip().lower(), STATUS_PASS)


@dataclasses.dataclass(frozen=True)
class JobRecord:
    """One job in a normalized trace.

    ``duration`` is the service time required at full allocation, in seconds.
    ``submit`` is seconds since trace start. ``gpus`` is the gang size: the
    job runs only when all ``gpus`` are simultaneously allocated
    (all-or-nothing gang semantics, SURVEY.md §2 "Gang scheduler mechanics").
    """

    job_id: int
    submit: float
    duration: float
    gpus: int
    tenant: int = 0
    status: int = STATUS_PASS

    def __post_init__(self):
        if self.duration <= 0:
            raise ValueError(f"job {self.job_id}: duration must be > 0")
        if self.gpus <= 0:
            raise ValueError(f"job {self.job_id}: gpus must be > 0")
        if self.submit < 0:
            raise ValueError(f"job {self.job_id}: submit must be >= 0")


@dataclasses.dataclass(frozen=True)
class ArrayTrace:
    """A trace as fixed-shape numpy arrays, padded to ``max_jobs``.

    Padding rows have ``valid == False`` and ``submit == +inf`` so they never
    arrive inside the jitted simulator. Sorted by submit time.
    """

    submit: np.ndarray    # [J] float32, +inf on padding
    duration: np.ndarray  # [J] float32, 1.0 on padding (never used)
    gpus: np.ndarray      # [J] int32, 0 on padding
    tenant: np.ndarray    # [J] int32
    valid: np.ndarray     # [J] bool

    @property
    def max_jobs(self) -> int:
        return int(self.submit.shape[0])

    @property
    def num_jobs(self) -> int:
        return int(self.valid.sum())

    def slice(self, start: int, count: int) -> "ArrayTrace":
        """A window of ``count`` jobs starting at the ``start``-th valid job,
        re-based so the first job submits at t=0. Used for episode windows."""
        idx = np.flatnonzero(self.valid)[start:start + count]
        recs = [JobRecord(int(i), float(self.submit[i]), float(self.duration[i]),
                          int(self.gpus[i]), int(self.tenant[i])) for i in idx]
        t0 = recs[0].submit if recs else 0.0
        recs = [dataclasses.replace(r, job_id=k, submit=r.submit - t0)
                for k, r in enumerate(recs)]
        return to_array_trace(recs, max_jobs=count)


def to_array_trace(jobs: Sequence[JobRecord], max_jobs: int | None = None) -> ArrayTrace:
    """Pack records into a padded, submit-sorted ArrayTrace."""
    jobs = sorted(jobs, key=lambda j: (j.submit, j.job_id))
    n = len(jobs)
    j = max_jobs if max_jobs is not None else n
    if n > j:
        raise ValueError(f"{n} jobs > max_jobs={j}")
    submit = np.full(j, np.inf, np.float32)
    duration = np.ones(j, np.float32)
    gpus = np.zeros(j, np.int32)
    tenant = np.zeros(j, np.int32)
    valid = np.zeros(j, bool)
    for k, job in enumerate(jobs):
        submit[k] = job.submit
        duration[k] = job.duration
        gpus[k] = job.gpus
        tenant[k] = job.tenant
        valid[k] = True
    return ArrayTrace(submit, duration, gpus, tenant, valid)


def from_array_trace(trace: ArrayTrace) -> list[JobRecord]:
    return [JobRecord(i, float(trace.submit[i]), float(trace.duration[i]),
                      int(trace.gpus[i]), int(trace.tenant[i]))
            for i in range(trace.max_jobs) if trace.valid[i]]
