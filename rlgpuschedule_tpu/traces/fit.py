"""Workload-distribution fitting + domain-randomized window generation (L0).

The domain engine (``rlgpuschedule_tpu.domains``) randomizes the ARRIVAL
half of a scenario — offered load, diurnal cycles, flash crowds, job-mix
scaling — but the base distributions those knobs perturb must come from
somewhere honest. This module fits them from the same sources the rest
of the trace layer uses:

- :func:`fit_jobs` summarizes any ``JobRecord`` list (a real Philly/PAI
  CSV via the loaders, or a generated proxy) into a :class:`TraceFit`:
  log-normal duration body (median + log-sigma), the empirical gang-size
  histogram, and the tenant count.
- :data:`PHILLY_FIT` / :data:`PAI_FIT` are the published-statistics
  presets (the exact constants ``philly_proxy`` generates from), so the
  no-CSV configs fit "for free".
- :func:`gen_domain_window` realizes one seeded episode window from a
  fit under a :class:`~..domains.DomainDraw`'s arrival knobs — the
  domain twin of ``synthetic.gen_poisson_trace``.

Fits are statistics, not copies: a domain window at ``load=1.0,
duration_scale=1.0`` is distribution-matched to its source, not
bit-equal — which is the point (one policy trained across the fit's
neighborhood, not one memorized trace).
"""
from __future__ import annotations

import dataclasses
import functools
import math
import zlib
from typing import Sequence

import numpy as np

from .records import ArrayTrace, JobRecord, to_array_trace
from .philly_proxy import (N_VIRTUAL_CLUSTERS, PAI_GPU_PROBS, PAI_GPU_SIZES,
                           PAI_MEDIAN_DURATION_S, PAI_DURATION_SIGMA,
                           PAI_N_TENANTS, PHILLY_GPU_PROBS, PHILLY_GPU_SIZES,
                           PHILLY_HOURLY, PHILLY_MEDIAN_DURATION_S,
                           PHILLY_DURATION_SIGMA, _diurnal_arrivals)
from .synthetic import DEFAULT_GPU_PROBS, DEFAULT_GPU_SIZES


@dataclasses.dataclass(frozen=True)
class TraceFit:
    """A workload's marginals, sufficient to regenerate its shape class:
    log-normal duration body (``median_duration_s`` + ``sigma``), gang-
    size histogram, tenant count. Frozen + hashable so it can ride
    config-keyed caches."""
    name: str
    median_duration_s: float
    sigma: float
    gpu_sizes: tuple[int, ...]
    gpu_probs: tuple[float, ...]
    n_tenants: int = 1
    # hour-of-day arrival-rate multipliers (24 bins, mean ~1.0) fitted
    # from the trace's own submit times; () = fall back to the
    # published-statistics PHILLY_HOURLY curve when diurnal shaping is
    # requested
    hourly: tuple[float, ...] = ()

    def __post_init__(self):
        if not (math.isfinite(self.median_duration_s)
                and self.median_duration_s > 0):
            raise ValueError(f"fit {self.name!r}: median_duration_s must "
                             f"be finite and > 0")
        if not (math.isfinite(self.sigma) and self.sigma >= 0):
            raise ValueError(f"fit {self.name!r}: sigma must be finite "
                             f"and >= 0")
        if len(self.gpu_sizes) != len(self.gpu_probs) or not self.gpu_sizes:
            raise ValueError(f"fit {self.name!r}: gpu_sizes/gpu_probs "
                             f"must be non-empty and matched")
        if any(s <= 0 for s in self.gpu_sizes):
            raise ValueError(f"fit {self.name!r}: gang sizes must be > 0")
        if any(p < 0 for p in self.gpu_probs) or sum(self.gpu_probs) <= 0:
            raise ValueError(f"fit {self.name!r}: gpu_probs must be "
                             f"non-negative with positive mass")
        if self.n_tenants <= 0:
            raise ValueError(f"fit {self.name!r}: n_tenants must be > 0")
        if self.hourly:
            if len(self.hourly) != 24:
                raise ValueError(f"fit {self.name!r}: hourly curve must "
                                 f"have 24 bins, got {len(self.hourly)}")
            if any(not math.isfinite(h) or h < 0 for h in self.hourly) \
                    or max(self.hourly) <= 0:
                raise ValueError(f"fit {self.name!r}: hourly curve must "
                                 f"be finite, non-negative, with a "
                                 f"positive peak")

    @property
    def mean_gpus(self) -> float:
        p = np.asarray(self.gpu_probs, np.float64)
        return float(np.dot(self.gpu_sizes, p / p.sum()))

    def mean_duration(self, duration_scale: float = 1.0) -> float:
        """Analytic log-normal mean at a scaled median."""
        return (self.median_duration_s * duration_scale
                * math.exp(0.5 * self.sigma ** 2))


def fit_hourly_curve(submit_s: "np.ndarray | Sequence[float]",
                     floor: float = 0.1) -> tuple[float, ...]:
    """Fit the piecewise hour-of-day arrival curve from submit
    timestamps (seconds; any epoch — only ``t mod 86400`` matters):
    per-hour arrival RATES (count / seconds of that hour-of-day inside
    the trace's span — exposure-normalized, so a span that is not a
    whole number of days does not double-weight the hours its partial
    day covers) normalized to mean 1.0. Deterministic — a histogram, no
    sampling. ``floor`` clamps the relative rate of empty/uncovered
    bins so a short trace still yields a curve the thinning sampler can
    run (a zero bin would make those hours unreachable forever)."""
    t = np.asarray(submit_s, np.float64)
    if t.size == 0:
        raise ValueError("cannot fit an hourly curve from zero arrivals")
    if not np.all(np.isfinite(t)):
        raise ValueError("submit times must be finite")
    day, hour = 86400.0, 3600.0
    hours = ((t % day) // hour).astype(np.int64)
    counts = np.bincount(hours, minlength=24).astype(np.float64)
    # per-bin exposure: seconds of [t0, t1] whose hour-of-day is h
    t0, t1 = float(t.min()), float(t.max())
    exposure = np.zeros(24, np.float64)
    for k in range(int(t0 // day), int(t1 // day) + 1):
        for h in range(24):
            lo, hi = k * day + h * hour, k * day + (h + 1) * hour
            exposure[h] += max(0.0, min(hi, t1) - max(lo, t0))
    covered = exposure > 0
    rate = np.zeros(24, np.float64)
    rate[covered] = counts[covered] / exposure[covered]
    mean_rate = rate[covered].mean() if covered.any() else 1.0
    if mean_rate <= 0:
        raise ValueError("cannot fit an hourly curve: zero arrival rate")
    curve = np.full(24, float(floor))
    curve[covered] = np.maximum(rate[covered] / mean_rate, float(floor))
    curve = curve * (24.0 / curve.sum())   # re-center mean at 1.0
    return tuple(float(h) for h in curve)


def fit_jobs(jobs: Sequence[JobRecord], name: str = "fit") -> TraceFit:
    """Fit a :class:`TraceFit` from records (real CSV loads or generated
    proxies): duration median + log-std, empirical gang histogram,
    observed tenant count, hour-of-day arrival curve."""
    if not jobs:
        raise ValueError("cannot fit an empty job list")
    dur = np.asarray([j.duration for j in jobs], np.float64)
    gpus = np.asarray([j.gpus for j in jobs], np.int64)
    sizes, counts = np.unique(gpus, return_counts=True)
    return TraceFit(
        name=name,
        median_duration_s=float(np.median(dur)),
        sigma=float(np.std(np.log(dur))),
        gpu_sizes=tuple(int(s) for s in sizes),
        gpu_probs=tuple(float(c) / len(jobs) for c in counts),
        n_tenants=int(max(j.tenant for j in jobs)) + 1,
        hourly=fit_hourly_curve([j.submit for j in jobs]))


# Published-statistics presets — identical constants to the proxy
# generators, so the no-CSV configs get an honest fit with no sampling.
PHILLY_FIT = TraceFit("philly", PHILLY_MEDIAN_DURATION_S,
                      PHILLY_DURATION_SIGMA, PHILLY_GPU_SIZES,
                      PHILLY_GPU_PROBS, N_VIRTUAL_CLUSTERS,
                      hourly=PHILLY_HOURLY)
PAI_FIT = TraceFit("pai", PAI_MEDIAN_DURATION_S, PAI_DURATION_SIGMA,
                   PAI_GPU_SIZES, PAI_GPU_PROBS, PAI_N_TENANTS)

_SYNTH_SIGMA = 1.0   # synthetic.gen_poisson_jobs' default log-sigma


@functools.lru_cache(maxsize=None)
def domain_fit(cfg) -> TraceFit:
    """The :class:`TraceFit` behind an ``ExperimentConfig``'s trace
    source: the synthetic generator's own parameters, the Philly/PAI
    published-statistics presets, or a fit of the actual CSV. Cached on
    the (frozen, hashable) config."""
    if cfg.trace == "synthetic":
        # gen_poisson_jobs draws lognormal(mu = ln(mean) - sigma^2/2), so
        # the body's median is mean * exp(-sigma^2/2)
        return TraceFit(
            "synthetic",
            cfg.mean_duration * math.exp(-0.5 * _SYNTH_SIGMA ** 2),
            _SYNTH_SIGMA, DEFAULT_GPU_SIZES, DEFAULT_GPU_PROBS,
            max(cfg.n_tenants, 1))
    if cfg.trace == "philly-proxy":
        return PHILLY_FIT
    if cfg.trace == "pai-proxy":
        return PAI_FIT
    if cfg.trace_path is None:
        raise ValueError(f"config {cfg.name!r} uses trace={cfg.trace!r} "
                         f"with no trace_path; cannot fit a job mix")
    if cfg.trace == "philly":
        from .philly import load_philly_jobs
        return fit_jobs(load_philly_jobs(cfg.trace_path), "philly-csv")
    if cfg.trace == "pai":
        from .pai import load_pai_jobs
        return fit_jobs(load_pai_jobs(cfg.trace_path), "pai-csv")
    raise ValueError(f"no fit recipe for trace={cfg.trace!r}")


def gen_domain_window(fit: TraceFit, n_jobs: int, seed, n_gpus: int,
                      load: float, duration_scale: float = 1.0,
                      burst_frac: float = 0.0, diurnal: bool = False,
                      max_gang: int | None = None,
                      n_tenants: int | None = None) -> ArrayTrace:
    """One seeded episode window from ``fit`` under a domain draw's
    arrival knobs, offered at ``load``x the capacity of THIS draw's
    ``n_gpus`` cluster (so a half-capacity geometry draw at load 1.1 is
    genuinely 1.1x oversubscribed, not accidentally 0.55x).

    ``seed`` may be an int or a tuple of ints (e.g. ``(base_seed, env,
    window_cursor)``) — the window-streaming path re-derives later
    windows by bumping the cursor component. ``max_gang`` renormalizes
    the gang mix to sizes the cluster can actually place (the proxy-
    generator recipe); a flash crowd collapses ``burst_frac`` of the
    jobs onto one burst instant."""
    if n_jobs <= 0:
        raise ValueError("n_jobs must be positive")
    if n_gpus <= 0:
        raise ValueError("n_gpus must be positive")
    if not (math.isfinite(load) and load > 0):
        raise ValueError(f"load must be finite and > 0, got {load}")
    if not (math.isfinite(duration_scale) and duration_scale > 0):
        raise ValueError(f"duration_scale must be finite and > 0, got "
                         f"{duration_scale}")
    if not 0.0 <= burst_frac <= 1.0:
        raise ValueError(f"burst_frac must be in [0, 1], got {burst_frac}")
    entropy = list(seed) if isinstance(seed, (tuple, list)) else [int(seed)]
    rng = np.random.default_rng(
        [zlib.crc32(("fit:" + fit.name).encode()),
         *[int(s) & 0xFFFFFFFF for s in entropy]])

    sizes = np.asarray(fit.gpu_sizes, np.int64)
    probs = np.asarray(fit.gpu_probs, np.float64)
    if max_gang is not None:
        keep = sizes <= max_gang
        if not keep.any():
            # a heavily shrunken geometry draw can under-run every fitted
            # gang size; single-GPU jobs are always placeable (capacity
            # sum >= 1 by the domain sampler's guard)
            sizes, probs = np.asarray([1]), np.asarray([1.0])
        else:
            sizes, probs = sizes[keep], probs[keep]
    probs = probs / probs.sum()
    mean_gpus = float(np.dot(sizes, probs))

    # rate = load * n_gpus / E[gpus * duration] (independent draws)
    rate = load * n_gpus / (mean_gpus * fit.mean_duration(duration_scale))
    if diurnal:
        submit = _diurnal_arrivals(rate, n_jobs, rng,
                                   hourly=(fit.hourly or PHILLY_HOURLY))
    else:
        submit = np.cumsum(rng.exponential(1.0 / rate, size=n_jobs))
    n_burst = int(round(burst_frac * n_jobs))
    if n_burst:
        # the crowd arrives mid-window on top of the background process
        burst_at = float(rng.uniform(0.2, 0.6) * submit[-1])
        submit[rng.choice(n_jobs, size=n_burst, replace=False)] = burst_at
    submit -= submit.min()       # first arrival at t=0, like gen_poisson_jobs

    mu = math.log(fit.median_duration_s * duration_scale)
    duration = np.maximum(1.0, rng.lognormal(mu, fit.sigma, size=n_jobs))
    gpus = rng.choice(sizes, size=n_jobs, p=probs)
    tenants = max(n_tenants if n_tenants is not None else fit.n_tenants, 1)
    tenant = rng.integers(0, tenants, size=n_jobs)
    jobs = [JobRecord(i, float(submit[i]), float(duration[i]),
                      int(gpus[i]), int(tenant[i]))
            for i in range(n_jobs)]
    return to_array_trace(jobs, max_jobs=n_jobs)
