"""L0 trace layer: normalized job records, loaders, synthetic generator."""
from .records import (JobRecord, ArrayTrace, to_array_trace, from_array_trace,
                      STATUS_PASS, STATUS_KILLED, STATUS_FAILED)
from .synthetic import gen_poisson_jobs, gen_poisson_trace
from .philly import load_philly, load_philly_jobs
from .pai import load_pai, load_pai_jobs
from .philly_proxy import (gen_philly_proxy_jobs, gen_philly_proxy_trace,
                           gen_pai_proxy_jobs, gen_pai_proxy_trace)
from .fit import (TraceFit, fit_jobs, domain_fit, gen_domain_window,
                  PHILLY_FIT, PAI_FIT)

__all__ = [
    "JobRecord", "ArrayTrace", "to_array_trace", "from_array_trace",
    "STATUS_PASS", "STATUS_KILLED", "STATUS_FAILED",
    "gen_poisson_jobs", "gen_poisson_trace",
    "load_philly", "load_philly_jobs", "load_pai", "load_pai_jobs",
    "gen_philly_proxy_jobs", "gen_philly_proxy_trace",
    "gen_pai_proxy_jobs", "gen_pai_proxy_trace",
    "TraceFit", "fit_jobs", "domain_fit", "gen_domain_window",
    "PHILLY_FIT", "PAI_FIT",
]
