// Fast discrete-event baseline-scheduler engine (L1 native runtime).
//
// Capability parity: SURVEY.md §2 "Event-driven sim engine" / "Baseline
// schedulers" — the C++ counterpart of sim/oracle.py + sim/schedulers.py
// for full-production-trace evaluation (SURVEY.md §3.4: Philly-scale
// traces are host-bound; the Python oracle's per-event Python loop is the
// bottleneck). Implements EXACTLY the oracle's semantics (verified by the
// cross-validation property tests in tests/test_native.py):
//
//   - gang all-or-nothing admission; jobs may span nodes, so feasibility
//     depends only on TOTAL free GPUs — per-node placement provably cannot
//     change any finish time and is not tracked here;
//   - preemption preserves attained service (RUNNING -> PENDING);
//   - time advances to min(next arrival, next completion, policy wake);
//     completions process before arrivals at the same instant (tolerance
//     1e-9, matching OracleSim.advance_to);
//   - policies: FIFO / SJF (non-preemptive greedy-skip over the pending
//     order) and SRTF / Tiresias-2D-LAS (preemptive greedy-budget prefix
//     admission over all in-system jobs, schedulers.py::schedule_step).
//
// Keys are frozen while a job is PENDING in all four policies (submit /
// duration / remaining / discretized attained service), so the pending set
// lives in an ordered std::multiset and each decision round walks it only
// until the free-GPU budget is exhausted; running jobs' keys (which do
// drift) are re-sorted fresh each round (|running| <= cluster capacity).
//
// C ABI (ctypes, see native/__init__.py):
//   run_baseline_native(n_jobs, submit[], duration[], gpus[],
//                       capacity, policy, thresholds[], n_thresholds,
//                       finish_out[], start_out[]) -> events (>=0) or
//                       error (<0); start_out = first-start times (the
//                       OracleSim.start surface; +inf if never started)

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <limits>
#include <set>
#include <vector>

namespace {

constexpr double INF = std::numeric_limits<double>::infinity();
constexpr double EPS = 1e-9;

enum Status : int8_t { NOT_ARRIVED = 0, PENDING = 1, RUNNING = 2, DONE = 3 };
enum Policy : int { FIFO = 0, SJF = 1, SRTF = 2, TIRESIAS = 3 };

struct Key {
  double k1, k2;
  int id;
  bool operator<(const Key& o) const {
    if (k1 != o.k1) return k1 < o.k1;
    if (k2 != o.k2) return k2 < o.k2;
    return id < o.id;
  }
};

struct Engine {
  int n;
  const double* submit;
  const double* duration;
  const int* gpus;
  int capacity;
  int policy;
  std::vector<double> thresholds;

  std::vector<int8_t> status;
  std::vector<double> remaining;
  std::vector<double> finish;
  std::vector<double> start;
  double clock = 0.0;
  int free_total;
  int n_done = 0;

  std::vector<int> arrival_order;  // job ids sorted by (submit, id)
  size_t next_arrival = 0;         // index into arrival_order
  std::multiset<Key> pending;      // frozen keys
  std::vector<int> running;

  double attained(int j) const {
    return (duration[j] - remaining[j]) * gpus[j];
  }

  double tier(int j) const {
    // Tiresias discretized queue index = count(thresholds <= attained),
    // matching np.searchsorted(th, attained, side="right")
    const double a = attained(j);
    size_t q = 0;
    while (q < thresholds.size() && a >= thresholds[q]) ++q;
    return static_cast<double>(q);
  }

  Key key_of(int j) const {
    switch (policy) {
      case FIFO: return {submit[j], 0.0, j};
      case SJF:  return {duration[j], 0.0, j};
      case SRTF: return {remaining[j], 0.0, j};
      default:   return {tier(j), submit[j], j};  // TIRESIAS
    }
  }

  void init() {
    status.assign(n, NOT_ARRIVED);
    remaining.assign(n, 0.0);
    finish.assign(n, INF);
    start.assign(n, INF);
    for (int j = 0; j < n; ++j) remaining[j] = duration[j];
    free_total = capacity;
    arrival_order.resize(n);
    for (int j = 0; j < n; ++j) arrival_order[j] = j;
    std::sort(arrival_order.begin(), arrival_order.end(), [&](int a, int b) {
      if (submit[a] != submit[b]) return submit[a] < submit[b];
      return a < b;
    });
    process_arrivals();
  }

  void process_arrivals() {
    while (next_arrival < arrival_order.size()) {
      const int j = arrival_order[next_arrival];
      if (submit[j] > clock) break;
      status[j] = PENDING;
      pending.insert(key_of(j));
      ++next_arrival;
    }
  }

  double next_event_time() const {
    double t = INF;
    if (next_arrival < arrival_order.size())
      t = submit[arrival_order[next_arrival]];
    for (const int j : running) t = std::min(t, clock + remaining[j]);
    return t;
  }

  // OracleSim.advance_to: completions (<= t within EPS) before arrivals.
  double advance_to(double t) {
    if (!std::isfinite(t)) return 0.0;
    const double dt = t - clock;
    clock = t;
    size_t w = 0;
    for (size_t i = 0; i < running.size(); ++i) {
      const int j = running[i];
      remaining[j] -= dt;
      if (remaining[j] <= EPS) {
        status[j] = DONE;
        finish[j] = t;
        remaining[j] = 0.0;
        free_total += gpus[j];
        ++n_done;
      } else {
        running[w++] = j;
      }
    }
    running.resize(w);
    process_arrivals();
    return dt;
  }

  void place(int j) {  // caller guarantees demand <= free_total
    free_total -= gpus[j];
    status[j] = RUNNING;
    start[j] = std::min(start[j], clock);
    running.push_back(j);
  }

  void preempt(int j) {
    free_total += gpus[j];
    status[j] = PENDING;
    pending.insert(key_of(j));  // remaining/attained frozen from here
  }

  // schedulers.py::schedule_step — one decision round at this instant.
  void schedule_step() {
    if (policy == FIFO || policy == SJF) {
      // greedy-skip over the pending order (each job tried independently)
      auto it = pending.begin();
      while (it != pending.end() && free_total > 0) {
        const int j = it->id;
        if (gpus[j] <= free_total) {
          it = pending.erase(it);
          place(j);
        } else {
          ++it;
        }
      }
      return;
    }
    // preemptive: greedy-budget prefix admission over in-system jobs in
    // priority order (merge re-sorted running with the pending multiset)
    std::vector<Key> run_keys;
    run_keys.reserve(running.size());
    for (const int j : running) run_keys.push_back(key_of(j));
    std::sort(run_keys.begin(), run_keys.end());

    int budget = free_total;
    for (const int j : running) budget += gpus[j];

    std::vector<int> admit_pending;
    std::vector<char> admit_running(n, 0);
    auto pit = pending.begin();
    auto rit = run_keys.begin();
    while (budget > 0 && (pit != pending.end() || rit != run_keys.end())) {
      const bool take_pending =
          rit == run_keys.end() ||
          (pit != pending.end() && *pit < *rit);
      const int j = take_pending ? pit->id : rit->id;
      if (gpus[j] <= budget) {
        budget -= gpus[j];
        if (take_pending) admit_pending.push_back(j);
        else admit_running[j] = 1;
      }
      if (take_pending) ++pit; else ++rit;
    }
    // preempt running jobs that fell out of the admitted set...
    std::vector<int> still;
    still.reserve(running.size());
    for (const int j : running) {
      if (admit_running[j]) still.push_back(j);
      else preempt(j);
    }
    running.swap(still);
    // ...then place admitted pending jobs (always feasible: total-GPU
    // budget admission == gang feasibility when jobs span nodes)
    for (const int j : admit_pending) {
      pending.erase(key_of(j));
      place(j);
    }
  }

  // tiresias::next_wake — earliest demotion-threshold crossing.
  double next_wake() const {
    if (policy != TIRESIAS) return INF;
    double t = INF;
    for (const int j : running) {
      const double a = attained(j);
      for (const double th : thresholds) {
        if (th > a) {
          t = std::min(t, clock + (th - a) / gpus[j]);
          break;
        }
      }
    }
    return t;
  }

  // schedulers.py::run_scheduler event loop.
  int64_t run(int64_t max_events) {
    init();
    for (int64_t e = 0; e < max_events; ++e) {
      schedule_step();
      if (n_done == n) return e;
      const double t = std::min(next_event_time(), next_wake());
      if (!std::isfinite(t)) return -2;  // deadlock
      if (advance_to(t) <= 0.0 && n_done != n) {
        if (advance_to(next_event_time()) == 0.0) return -3;  // no progress
      }
    }
    return -4;  // max_events exceeded
  }
};

}  // namespace

extern "C" int64_t run_baseline_native(
    int n_jobs, const double* submit, const double* duration,
    const int* gpus, int capacity, int policy, const double* thresholds,
    int n_thresholds, double* finish_out, double* start_out) {
  if (n_jobs < 0 || capacity <= 0 || policy < 0 || policy > 3) return -1;
  for (int j = 0; j < n_jobs; ++j)
    if (gpus[j] > capacity || gpus[j] <= 0 || duration[j] <= 0.0) return -1;
  Engine eng;
  eng.n = n_jobs;
  eng.submit = submit;
  eng.duration = duration;
  eng.gpus = gpus;
  eng.capacity = capacity;
  eng.policy = policy;
  eng.thresholds.assign(thresholds, thresholds + n_thresholds);
  std::sort(eng.thresholds.begin(), eng.thresholds.end());
  const int64_t events = eng.run(10'000'000LL);
  if (events < 0) return events;
  for (int j = 0; j < n_jobs; ++j) {
    finish_out[j] = eng.finish[j];
    start_out[j] = eng.start[j];
  }
  return events;
}
