"""Native runtime (L1): compile-on-demand C++ baseline engine + ctypes
binding.

Capability parity: SURVEY.md §2 "Native components" — the reference keeps
its native code in dependencies (PyTorch CUDA kernels, NCCL); this
framework's TPU compute path is XLA-compiled JAX, and the host-side
runtime piece that IS performance-critical — full-production-trace
baseline scheduling for the JCT comparison tables (SURVEY.md §3.4) — is
implemented natively here (``fast_oracle.cpp``) and cross-validated
against the Python oracle property-by-property.

The shared library is built on first use with the system ``g++`` (no build
system, no pybind11 — plain C ABI via ctypes), cached next to the source
keyed by source hash, and every entry point degrades gracefully to the
Python oracle when no toolchain is present (``available()`` gates it).
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import shutil
import subprocess

import numpy as np

_SRC = os.path.join(os.path.dirname(__file__), "fast_oracle.cpp")
_POLICIES = {"fifo": 0, "sjf": 1, "srtf": 2, "tiresias": 3}
_TIRESIAS_THRESHOLDS = (3600.0, 36000.0)   # sim/schedulers.py::tiresias

_lib: ctypes.CDLL | None = None
_build_error: str | None = None


def _so_path() -> str:
    # user-owned 0700 cache dir, NOT the shared tmp dir: a predictable
    # world-writable path could be pre-seeded by another local user and
    # dlopen runs arbitrary constructors
    cache = os.environ.get("XDG_CACHE_HOME",
                           os.path.join(os.path.expanduser("~"), ".cache"))
    d = os.path.join(cache, "rlgpuschedule_tpu")
    os.makedirs(d, mode=0o700, exist_ok=True)
    with open(_SRC, "rb") as f:
        tag = hashlib.sha256(f.read()).hexdigest()[:16]
    return os.path.join(d, f"fast_oracle_{tag}.so")


def _load() -> ctypes.CDLL | None:
    global _lib, _build_error
    if _lib is not None or _build_error is not None:
        return _lib
    cxx = shutil.which("g++") or shutil.which("c++") or shutil.which("clang++")
    if cxx is None:
        _build_error = "no C++ compiler on PATH"
        return None
    so = _so_path()
    if not os.path.exists(so):
        tmp = so + f".tmp{os.getpid()}"
        cmd = [cxx, "-O2", "-std=c++17", "-shared", "-fPIC", _SRC, "-o", tmp]
        try:
            subprocess.run(cmd, check=True, capture_output=True, text=True,
                           timeout=120)
            os.replace(tmp, so)  # atomic: concurrent builders race safely
        except (subprocess.SubprocessError, OSError) as e:
            _build_error = f"build failed: {getattr(e, 'stderr', e)}"
            return None
    lib = ctypes.CDLL(so)
    f = lib.run_baseline_native
    f.restype = ctypes.c_int64
    f.argtypes = [
        ctypes.c_int,
        np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
        ctypes.c_int, ctypes.c_int,
        np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
        ctypes.c_int,
        np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
    ]
    _lib = lib
    return _lib


def available() -> bool:
    """True iff the native engine can be built/loaded on this machine."""
    return _load() is not None


def build_error() -> str | None:
    _load()
    return _build_error


def run_baseline_native(trace, n_nodes: int, gpus_per_node: int, name: str,
                        thresholds=_TIRESIAS_THRESHOLDS,
                        ) -> tuple[np.ndarray, np.ndarray]:
    """Run one named baseline natively over an ArrayTrace; returns per-row
    ``(finish, start)`` times [max_jobs] (+inf on padding — all valid jobs
    complete, as in the oracle; ``start`` is the FIRST start, preserved
    across preemptions, mirroring ``OracleSim.start``). Raises RuntimeError
    if the engine is unavailable or the trace is infeasible."""
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native engine unavailable: {_build_error}")
    if name not in _POLICIES:
        raise ValueError(f"unknown baseline {name!r}")
    valid = np.flatnonzero(trace.valid)
    submit = np.ascontiguousarray(trace.submit[valid], np.float64)
    duration = np.ascontiguousarray(trace.duration[valid], np.float64)
    gpus = np.ascontiguousarray(trace.gpus[valid], np.int32)
    th = np.ascontiguousarray(sorted(thresholds), np.float64)
    finish = np.full(len(valid), np.inf, np.float64)
    start = np.full(len(valid), np.inf, np.float64)
    rc = lib.run_baseline_native(
        len(valid), submit, duration, gpus, n_nodes * gpus_per_node,
        _POLICIES[name], th, len(th), finish, start)
    if rc < 0:
        reasons = {-1: "invalid input (zero/oversized gang or duration)",
                   -2: "scheduler deadlock", -3: "no progress",
                   -4: "max_events exceeded"}
        raise RuntimeError(f"native {name} failed: "
                           f"{reasons.get(int(rc), rc)}")
    finish_out = np.full(trace.max_jobs, np.inf, np.float64)
    start_out = np.full(trace.max_jobs, np.inf, np.float64)
    finish_out[valid] = finish
    start_out[valid] = start
    return finish_out, start_out


class NativeSimResult:
    """Finished-run shim exposing the OracleSim result surface the eval
    harness and downstream tools read: ``finish`` / ``start`` / ``status``
    / ``jcts()`` / ``avg_jct()`` / ``trace`` (the ``sim.schedulers
    .BaselineResult`` protocol). ``status`` mirrors the oracle's finished
    state exactly: all rows DONE — valid jobs because the engine runs the
    trace to completion, padding rows because ``OracleSim.__init__`` marks
    them DONE from the start (oracle.py:95)."""

    def __init__(self, trace, finish: np.ndarray, start: np.ndarray):
        from ..sim.oracle import DONE

        self.trace = trace
        self.finish = np.where(np.isfinite(finish), finish, np.nan)
        self.start = np.where(np.isfinite(start), start, np.nan)
        self.status = np.full(trace.max_jobs, DONE, np.int32)

    def jcts(self) -> np.ndarray:
        v = self.trace.valid & np.isfinite(self.finish)
        return (self.finish[v] - self.trace.submit[v]).astype(np.float64)

    def avg_jct(self) -> float:
        j = self.jcts()
        return float(j.mean()) if len(j) else float("nan")
