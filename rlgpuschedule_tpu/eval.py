"""Evaluation / trace replay (L6): policy JCT vs baseline schedulers.

Capability parity: SURVEY.md §3.4 — "run trained policy (or baseline) over
full trace, report JCT table" — the harness behind north-star metric #2
(avg JCT on the Philly trace vs Tiresias, SURVEY.md §0/§6).

The policy side is a deterministic (greedy-argmax) replay of the jitted
environment: one ``lax.scan`` per window batch, frozen per-env once the
episode completes, so the whole evaluation is a single XLA program. The
baseline side replays the same windows through the oracle event-driven sim
(``sim.schedulers``), giving an apples-to-apples avg-JCT table.
"""
from __future__ import annotations

import contextlib
import dataclasses
import functools
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from .algos import action_dist
from .decision import (gate_stalled, greedy_actions, policy_decision,
                       preempt_slice, stall_threshold)
from .env import env as env_lib
from .env import hier as hier_lib
from .env.env import EnvParams
from .env.hier import HierParams
from .sim import core
from .sim.oracle import DONE as DONE_STATUS
from .sim.oracle import NOT_ARRIVED as NOT_ARRIVED_STATUS
from .sim.oracle import PENDING as PENDING_STATUS
from .sim.oracle import RUNNING as RUNNING_STATUS
from .sim.schedulers import run_baseline
from .traces.records import ArrayTrace


class EvalResult(NamedTuple):
    """Per-window-batch evaluation outcome (device arrays, [E] leading)."""
    avg_jct: jax.Array      # f32[E] mean JCT over completed jobs
    n_done: jax.Array       # i32[E] completed valid jobs
    n_valid: jax.Array      # i32[E] valid jobs in the window
    makespan: jax.Array     # f32[E] final sim clock
    utilization: jax.Array  # f32[E] time-averaged GPU busy fraction
    steps: jax.Array        # i32[E] decision steps taken


# the decision rule (greedy argmax over masked logits + the stall gate)
# is shared with the serving path — rlgpuschedule_tpu.decision is the one
# definition both consume, so serve and eval cannot drift (PR 7). These
# module-private names stay as aliases for in-repo callers.
_greedy_actions = greedy_actions
_preempt_slice = preempt_slice
_stall_threshold = stall_threshold


def _random_actions(key: jax.Array, mask: Any) -> Any:
    logits = jax.tree.map(lambda m: jnp.where(m, 0.0, -1e9), mask)
    actions, _ = action_dist.sample(key, logits)
    return actions


def _gate_to_fifo(env_params: EnvParams, sim_status: jax.Array,
                  mask: jax.Array, actions: jax.Array,
                  gate: int) -> jax.Array:
    """Backlog-gated hybrid scheduler: when fewer than ``gate`` jobs are
    PENDING, play FIFO instead of the learned policy — place the OLDEST
    pending job whose gang fits (the queue is submit-sorted, so the
    lowest feasible slot, pack mode, is FIFO-with-backfill — the same
    greedy admit-in-order-while-it-fits rule the oracle baselines use,
    ``sim.schedulers.run_scheduler``); no-op/advance only when nothing
    fits; never preempt.

    Measured motivation (BASELINE.md config-4 full-trace): a policy
    trained to triage deep backlogs adds ordering delay on an UNDERLOADED
    stream where the right move is always "place immediately" — every
    baseline ties there, so falling through to FIFO below a shallow-
    backlog threshold recovers the tie while keeping the learned policy
    where scheduling is actually hard. (A first cut placed only the queue
    HEAD — strict no-backfill FIFO — and measured WORSE than no gate:
    one blocked wide gang stalls the whole queue. Backfill is
    load-bearing.) Works for batched ([E, J] status) and single-env
    ([J]) calls alike."""
    sim = env_params.sim
    K, P, R = sim.queue_len, sim.n_placements, sim.preempt_len
    pending = jnp.sum(sim_status == PENDING_STATUS, axis=-1)
    # preference: oldest slot first (pack before spread within a slot),
    # then no-op; preempt slots below the valid range so FIFO never evicts
    prefs = jnp.concatenate([
        jnp.arange(K * P, 0, -1, dtype=jnp.float32),
        jnp.full((R,), -1.0),
        jnp.array([0.5], jnp.float32),
    ])
    fifo = jnp.argmax(jnp.where(mask, prefs, -jnp.inf),
                      axis=-1).astype(actions.dtype)
    return jnp.where(pending < gate, fifo, actions)


class _EnvOps(NamedTuple):
    """The env-specific slice of the replay loop (flat vs hierarchical)."""
    step: Callable          # (state, trace, action) -> (state', ts)
    capacity: int
    busy: Callable          # batched state -> f32[E] allocated GPUs
    jct_stats: Callable     # (state, trace) -> {avg_jct, n_done, ...}
    makespan: Callable      # batched state -> f32[E]


def _env_ops(params) -> _EnvOps:
    if isinstance(params, HierParams):
        return _EnvOps(
            step=lambda s, tr, a: hier_lib.step(params, s, tr, a),
            capacity=params.n_pods * params.pod_capacity,
            busy=lambda s: jnp.sum(s.pods.alloc, axis=(1, 2, 3)
                                   ).astype(jnp.float32),
            jct_stats=hier_lib.jct_stats,
            makespan=lambda s: s.pods.clock[:, 0])
    return _EnvOps(
        step=lambda s, tr, a: env_lib.step(params, s, tr, a),
        capacity=params.sim.capacity,
        busy=lambda s: jnp.sum(s.sim.alloc, axis=(1, 2)).astype(jnp.float32),
        jct_stats=lambda s, tr: core.jct_stats(s.sim, tr),
        makespan=lambda s: s.sim.clock)


def replay(apply_fn: Callable, net_params: Any,
           env_params: "EnvParams | HierParams",
           traces: core.Trace, max_steps: int | None = None,
           policy: str = "greedy", key: jax.Array | None = None,
           return_states: bool = False, backlog_gate: int = 0,
           stall_guard: bool = True, faults: Any = None,
           ) -> "EvalResult | tuple[EvalResult, Any]":
    """Deterministically replay the batched trace windows under the policy
    (flat configs 1-4 and the hierarchical config 5 share this harness).

    Unlike training rollouts there is NO auto-reset: each env runs its
    window to completion (or ``max_steps``) and is then frozen — the scan
    keeps stepping the other envs, masking out the finished ones, which is
    the static-shape replacement for the oracle's per-window event loop.

    ``policy``: "greedy" (argmax over masked logits — deterministic replay,
    SURVEY.md §3.4) or "random" (masked-uniform; the learning-smoke-test
    baseline, SURVEY.md §4 "policy beats random").

    ``backlog_gate``: >0 evaluates the backlog-gated HYBRID scheduler —
    see :func:`_gate_to_fifo` (flat configs only).

    ``faults`` (flat configs): batched per-env ``sim.faults.FaultSchedule``
    replayed next to the traces — the chaos matrix's policy side. A
    faulty-cluster episode may legitimately end sub-100% complete (a
    permanently-drained node can strand work); completion is part of the
    reported degradation, not an error.

    ``stall_guard`` (preemptive configs, greedy replay only): break the
    measured place↔preempt argmax deadlock (BASELINE.md config-1p: 1 of 8
    held-out drain windows froze at 87.7% completion, invariant to
    horizon — a zero-sim-time cycle the anti-stall TRAINING charge cannot
    reach because argmax replay has no exploration). Mechanism: count
    consecutive zero-dt decision steps per env; past
    :func:`_stall_threshold` (the bound on legitimate same-instant
    activity) mask every preempt action until the clock next advances.
    With preempts held, a zero-dt run is finite — each placement removes
    a pending job and no-op advances to the next event — so every cycle
    terminates; sub-threshold behavior is bit-identical to the unguarded
    replay.
    """
    if policy not in ("greedy", "random"):
        raise ValueError(f"unknown replay policy {policy!r}; "
                         f"expected 'greedy' or 'random'")
    if backlog_gate < 0:
        raise ValueError("backlog_gate must be >= 0 (a negative gate "
                         "never engages — silently ungated)")
    if backlog_gate and policy == "random":
        raise ValueError("backlog_gate composes with the LEARNED policy "
                         "only: gating the random control would overwrite "
                         "its actions with FIFO whenever the backlog is "
                         "shallow, silently inflating the baseline")
    if backlog_gate and isinstance(env_params, HierParams):
        raise ValueError("backlog_gate applies to flat configs (the "
                         "hierarchical action space has no single FIFO "
                         "fall-through action)")
    if faults is not None and isinstance(env_params, HierParams):
        raise ValueError("fault replay applies to flat configs (the "
                         "hierarchical env has no fault-process support)")
    max_steps = int(max_steps or env_params.horizon)
    if key is None:
        key = jax.random.PRNGKey(0)
    state, ts = env_lib.vec_reset(env_params, traces, faults)

    ops = _env_ops(env_params)
    if faults is None:
        step_one = jax.vmap(ops.step)
    else:
        step_one = jax.vmap(
            lambda s, tr, a, f: env_lib.step(env_params, s, tr, a, f))
    pre = (_preempt_slice(env_params)
           if stall_guard and policy == "greedy" else None)
    thresh = _stall_threshold(env_params) if pre is not None else 0

    def scan_step(carry, k):
        state, obs, mask, done, busy_time, stall = carry
        if pre is not None:
            mask = gate_stalled(mask, stall, thresh, pre)
        if policy == "random":
            actions = _random_actions(k, mask)
        else:
            actions = policy_decision(apply_fn, net_params, obs, mask)
        if backlog_gate:
            actions = _gate_to_fifo(env_params, state.sim.status, mask,
                                    actions, backlog_gate)
        new_state, new_ts = (step_one(state, traces, actions)
                             if faults is None else
                             step_one(state, traces, actions, faults))
        dt = jnp.where(done, 0.0, new_ts.info.dt)
        busy_time = busy_time + ops.busy(state) * dt
        stall = jnp.where(done | (new_ts.info.dt > 0.0), 0, stall + 1)
        # freeze finished envs: keep the old state/obs/mask once done
        keep = lambda old, new: jnp.where(
            done.reshape((-1,) + (1,) * (new.ndim - 1)), old, new)
        tkeep = lambda old, new: jax.tree.map(keep, old, new)
        state = tkeep(state, new_state)
        obs = tkeep(obs, new_ts.obs)
        mask = tkeep(mask, new_ts.action_mask)
        done = done | new_ts.done
        return (state, obs, mask, done, busy_time, stall), None

    keys = jax.random.split(key, max_steps)
    init = (state, ts.obs, ts.action_mask,
            jnp.zeros(ts.done.shape, bool),
            jnp.zeros(ts.done.shape, jnp.float32),
            jnp.zeros(ts.done.shape, jnp.int32))
    (state, _, _, done, busy_time, _), _ = jax.lax.scan(scan_step, init,
                                                        keys)

    stats = jax.vmap(ops.jct_stats)(state, traces)
    makespan = ops.makespan(state)
    util = busy_time / (jnp.maximum(makespan, 1e-6) * ops.capacity)
    result = EvalResult(avg_jct=stats["avg_jct"],
                        n_done=stats["n_done"].astype(jnp.int32),
                        n_valid=jnp.sum(traces.valid,
                                        axis=1).astype(jnp.int32),
                        makespan=makespan, utilization=util,
                        steps=state.t)
    if return_states:
        return result, state
    return result


def _shift_schedule(fs, base: float):
    """Rebase a GLOBAL-time fault/domain schedule onto a stitched window's
    LOCAL clock (window time 0 = global ``base``): down windows fully in
    the past collapse to never-active (+inf/+inf), a drain straddling
    ``base`` becomes active from local 0, future windows shift left.
    Slowdown and capacity are time-invariant and pass through — the
    returned value keeps the input's type (``_replace``), so a
    DomainSchedule stays a DomainSchedule."""
    start = np.asarray(fs.down_start, np.float64) - base
    end = np.asarray(fs.down_end, np.float64) - base
    past = end <= 0.0
    start = np.where(past, np.inf, np.maximum(start, 0.0))
    end = np.where(past, np.inf, end)
    return fs._replace(down_start=start.astype(np.float32),
                       down_end=end.astype(np.float32))


def full_trace_replay(apply_fn: Callable, net_params: Any,
                      env_params: EnvParams, source: ArrayTrace,
                      max_steps_per_window: int | None = None,
                      policy: str = "greedy",
                      key: jax.Array | None = None,
                      backlog_gate: int = 0,
                      stall_guard: bool = True,
                      drain_completions: int = 1,
                      faults=None) -> dict[str, Any]:
    """Policy avg-JCT over an ENTIRE source trace via sequential windowed
    replay with residual carry (VERDICT r1 missing #4) — one number
    comparable to the ``native``/oracle baselines over the same trace
    (SURVEY.md §3.4, north-star #2).

    ``faults``: ONE unbatched :class:`~.sim.faults.FaultSchedule` (or
    :class:`~.domains.DomainSchedule` — randomized geometry/speed) in
    GLOBAL trace time, spanning the whole stream. Each stitched window
    replays under the schedule rebased onto its local clock
    (:func:`_shift_schedule`): same shapes every window, so the one
    compiled window program still serves the entire stitch. Baselines
    comparing against this number must run under the SAME schedule in
    global time (``run_baseline(faults=...)`` — the oracle keeps one
    global clock, so no shifting there).

    The trace streams through a fixed-shape job table of ``max_jobs``
    rows: each window holds the carried residual jobs (anything not DONE
    at the previous cutoff) plus as many fresh jobs as fit, and replays
    under the policy only up to the arrival time of the first EXCLUDED
    job (the cutoff) — so a window never runs ahead of workload it cannot
    see. When the first excluded job has ALREADY arrived (deep backlog:
    global time has outrun the arrival process, so the cutoff is in the
    past), the window instead runs just until it completes one job —
    freeing a table row — and global time advances by the sim time
    actually consumed. Global time is the running sum of those advances,
    and JCT is accounted against original submit times. (Round-3 fix: the
    pre-fix code let the already-arrived cutoff go NEGATIVE, moving
    global time backward and silently deleting queueing delay — stitched
    avg JCT stayed flat as the backlog grew while every true-sim baseline
    grew linearly. tests/test_eval.py pins windowed-FIFO ≈ oracle FIFO on
    an overloaded trace.)

    The stitched number is exact up to two documented approximations:

    - a job RUNNING at a window boundary is carried as PENDING with its
      remaining service (checkpointed preemption — the sim's preemption
      model);
    - a future cutoff freezes the window at the last decision point not
      beyond it, so service between that point and the cutoff is re-run
      next window (conservative: never undercounts JCT).

    The per-window program is jitted ONCE (fixed shapes) and reused for
    every window.

    ``drain_completions``: in deep-backlog mode, freeze after this many
    completions instead of 1, ingesting that many fresh jobs per window.
    The default (1) reproduces the recorded round-3 tables bit-for-bit but
    makes window count linear in the backlog EXCESS — a sustained-overload
    100k-job stream would stitch ~10^5 windows. Batching completions cuts
    the window count ~``drain_completions``× and REDUCES the seam-carry
    tax (fewer seams); the cost is that already-arrived excluded jobs stay
    invisible to the policy for up to that many completions longer (they
    would sit at the tail of a backlog far deeper than the policy's queue
    view anyway). Clamped to ``max_jobs // 2`` so every deep window still
    ingests fresh work alongside its residuals.
    """
    if policy not in ("greedy", "random"):
        raise ValueError(f"unknown replay policy {policy!r}; "
                         f"expected 'greedy' or 'random'")
    if backlog_gate < 0:
        raise ValueError("backlog_gate must be >= 0 (a negative gate "
                         "never engages — silently ungated)")
    if backlog_gate and policy == "random":
        raise ValueError("backlog_gate composes with the LEARNED policy "
                         "only: gating the random control would overwrite "
                         "its actions with FIFO whenever the backlog is "
                         "shallow, silently inflating the baseline")
    if key is None:
        key = jax.random.PRNGKey(0)
    if drain_completions < 1:
        raise ValueError("drain_completions must be >= 1 (a deep-backlog "
                         "window must free at least one table row)")
    sim = env_params.sim
    if faults is not None and faults.down_start.shape[-2] != sim.n_nodes:
        raise ValueError(
            f"schedule covers {faults.down_start.shape[-2]} nodes; the "
            f"stitch cluster has {sim.n_nodes}")
    J = sim.max_jobs
    drain_block = min(int(drain_completions), max(J // 2, 1))
    S = int(max_steps_per_window or 4 * J + 16)
    # replay wants no horizon cut: only completion / cutoff freeze
    rp = dataclasses.replace(env_params, horizon=S + 1)
    pre = (_preempt_slice(env_params)
           if stall_guard and policy == "greedy" else None)
    thresh = _stall_threshold(env_params) if pre is not None else 0

    @jax.jit
    def _window(net_params, trace: core.Trace, cutoff, need_completion,
                wkey, schedule=None):
        """One window replay. ``cutoff``: local freeze time (+inf = run to
        completion). ``need_completion`` (deep-backlog mode): ignore the
        clock until one valid job completes, then freeze — the step that
        completes is KEPT (its clock is the window's true span), unlike
        the future-cutoff mode where the overshooting step is discarded.
        ``schedule``: this window's LOCAL-time fault/domain schedule
        (``_shift_schedule``); a traced arg, so every window reuses the
        one compiled program."""
        state, ts = env_lib.reset(rp, trace, schedule)

        def scan_step(carry, k):
            state, obs, mask, frozen, stall = carry
            if pre is not None:
                # same zero-dt cycle breaker as replay(): see its docstring
                mask = gate_stalled(mask, stall, thresh, pre)
            if policy == "random":
                # masked-uniform; _random_actions expects a batch axis
                action = jax.tree.map(
                    lambda a: a[0],
                    _random_actions(k, jax.tree.map(lambda m: m[None], mask)))
            else:
                action = policy_decision(apply_fn, net_params, obs, mask)
            if backlog_gate:
                action = _gate_to_fifo(rp, state.sim.status, mask,
                                       action, backlog_gate)
            new_state, new_ts = env_lib.step(rp, state, trace, action,
                                             schedule)
            done_before = jnp.sum(
                (state.sim.status == DONE_STATUS) & trace.valid)
            # future cutoff: discard any step past it. already-arrived
            # cutoff: run freely until drain_block completions exist,
            # then freeze
            gate = jnp.where(need_completion, done_before >= drain_block,
                             True)
            stop = frozen | ((new_state.sim.clock > cutoff) & gate)
            keep = lambda old, new: jax.tree.map(
                lambda o, n: jnp.where(stop, o, n), old, new)
            state = keep(state, new_state)
            obs = keep(obs, new_ts.obs)
            mask = keep(mask, new_ts.action_mask)
            frozen = stop | new_ts.done
            stall = jnp.where(frozen | (new_ts.info.dt > 0.0), 0, stall + 1)
            return (state, obs, mask, frozen, stall), None

        init = (state, ts.obs, ts.action_mask, jnp.bool_(False),
                jnp.int32(0))
        (state, _, _, _, _), _ = jax.lax.scan(scan_step, init,
                                              jax.random.split(wkey, S))
        # future-cutoff freeze keeps the last decision point NOT beyond the
        # cutoff; between that clock and the cutoff there are no events (the
        # next one overshot), only continuous service — advance it, or
        # running jobs lose (cutoff − clock) of work at EVERY window seam
        # (measured ~2× JCT over-count on an overloaded 2k-job trace)
        t_end = jnp.minimum(cutoff, core.next_event_time(state.sim, trace,
                                                         schedule))
        t_end = jnp.maximum(t_end, state.sim.clock)
        sim = core.advance_to(
            state.sim, trace,
            jnp.where(jnp.isfinite(t_end), t_end, state.sim.clock),
            schedule)
        return state._replace(sim=sim)

    valid = np.flatnonzero(np.asarray(source.valid))
    submit = np.asarray(source.submit, np.float64)[valid]
    duration = np.asarray(source.duration, np.float64)[valid]
    gpus = np.asarray(source.gpus, np.int32)[valid]
    tenant = np.asarray(source.tenant, np.int32)[valid]
    total = len(valid)
    if total == 0:
        raise ValueError("source trace has no valid jobs")
    # on a randomized-geometry cluster the binding bound is the DRAWN
    # capacity, not the static one — a gang wider than the shrunken
    # cluster would pend forever and trip the no-progress guard below
    cap = getattr(faults, "capacity", None)
    total_gpus = int(np.asarray(cap).sum()) if cap is not None \
        else sim.capacity
    if int(gpus.max()) > total_gpus:
        raise ValueError(
            f"source demands up to {int(gpus.max())} GPUs but the "
            f"{'drawn' if cap is not None else 'static'} cluster has "
            f"{total_gpus}; clamp the trace first "
            f"(sim.core.validate_trace(clamp=True)) or use a milder "
            f"domain draw")

    finish_g = np.full(total, np.nan)       # global finish times
    # residuals: original index -> remaining service
    res_idx = np.zeros(0, np.int64)
    res_rem = np.zeros(0, np.float64)
    base, cursor, n_windows = 0.0, 0, 0
    max_windows = 2 * total + 16   # ≥1 fresh job ingested per window
    while cursor < total or len(res_idx):
        n_windows += 1
        if n_windows > max_windows:
            raise RuntimeError(
                f"full-trace replay made no progress after {n_windows} "
                f"windows ({cursor}/{total} ingested, {len(res_idx)} "
                f"residual)")
        n_fresh = min(J - len(res_idx), total - cursor)
        fresh = np.arange(cursor, cursor + n_fresh)
        rows_idx = np.concatenate([res_idx, fresh])
        rows_rem = np.concatenate([res_rem, duration[fresh]])
        # rows must be submit-sorted (the sim's queue order contract); a
        # carried not-yet-arrived residual can out-submit a fresh job
        order = np.lexsort((rows_idx,
                            np.maximum(submit[rows_idx] - base, 0.0)))
        rows_idx, rows_rem = rows_idx[order], rows_rem[order]
        n_rows = len(rows_idx)
        cutoff = (submit[cursor + n_fresh] - base
                  if cursor + n_fresh < total else np.inf)
        # deep backlog: the first excluded job has already arrived (global
        # time outran the arrival process) — run only until one completion
        # frees a row, so the waiting job is ingested ASAP
        need_completion = bool(np.isfinite(cutoff) and cutoff <= 0.0)
        if need_completion:
            cutoff = 0.0

        w_submit = np.full(J, np.inf, np.float32)
        w_duration = np.ones(J, np.float32)
        w_gpus = np.zeros(J, np.int32)
        w_tenant = np.zeros(J, np.int32)
        w_valid = np.zeros(J, bool)
        w_submit[:n_rows] = np.maximum(submit[rows_idx] - base, 0.0)
        w_duration[:n_rows] = rows_rem
        w_gpus[:n_rows] = gpus[rows_idx]
        w_tenant[:n_rows] = tenant[rows_idx]
        w_valid[:n_rows] = True
        trace = core.Trace.from_array_trace(ArrayTrace(
            w_submit, w_duration, w_gpus, w_tenant, w_valid))

        key, wkey = jax.random.split(key)
        sched = _shift_schedule(faults, base) if faults is not None \
            else None
        state = _window(net_params, trace, jnp.float32(cutoff),
                        jnp.bool_(need_completion), wkey, sched)
        s = core.np_state(state.sim)
        done_rows = w_valid & (s.status == DONE_STATUS)
        finish_g[rows_idx[done_rows[:n_rows]]] = \
            base + s.finish[:n_rows][done_rows[:n_rows]]
        left = w_valid[:n_rows] & (s.status[:n_rows] != DONE_STATUS)
        res_idx = rows_idx[left]
        res_rem = np.asarray(s.remaining, np.float64)[:n_rows][left]
        # future cutoff: global time jumps to the excluded arrival.
        # completion mode / final drain: advance by sim time consumed
        base = base + (cutoff if np.isfinite(cutoff) and not need_completion
                       else float(s.clock))
        cursor += n_fresh

    jct = finish_g - submit
    assert np.isfinite(jct).all()
    return {"avg_jct": float(jct.mean()), "n_jobs": total,
            "jct": jct, "finish": finish_g, "tenant": tenant,
            "windows": n_windows, "makespan": float(np.nanmax(finish_g)),
            # EFFECTIVE batching after the max_jobs//2 clamp — the value
            # that determines the replay, not the request
            "drain_completions": drain_block}


def pooled_avg_jct(result: EvalResult) -> tuple[float, float]:
    """Completion-weighted mean JCT across windows + completed fraction."""
    n = np.asarray(result.n_done, np.float64)
    jct = np.asarray(result.avg_jct, np.float64)
    total = n.sum()
    frac = float(total / max(np.asarray(result.n_valid).sum(), 1))
    return float((jct * n).sum() / max(total, 1.0)), frac


def _pct_row(jcts: np.ndarray,
             percentiles: tuple[float, ...]) -> dict[str, float]:
    """One scheduler's tail-latency columns, e.g. {"p50": .., "p99": ..}."""
    return {f"p{g:g}": float(np.percentile(jcts, g))
            for g in percentiles} if jcts.size else {}


def baseline_jcts(windows: list[ArrayTrace], n_nodes: int,
                  gpus_per_node: int, name: str) -> np.ndarray:
    """Pooled per-job JCTs of one baseline over the windows (completed
    valid jobs only) — the array behind both the mean and the percentile
    columns."""
    jcts = [run_baseline(w, n_nodes, gpus_per_node, name).jcts()
            for w in windows]
    return np.concatenate(jcts) if jcts else np.zeros(0)


def baseline_jct_table(windows: list[ArrayTrace], n_nodes: int,
                       gpus_per_node: int,
                       names: tuple[str, ...] = ("fifo", "sjf", "srtf",
                                                 "tiresias"),
                       ) -> dict[str, float]:
    """Completion-weighted avg JCT per baseline over the same windows the
    policy is evaluated on (oracle event-driven replay, SURVEY.md §3.4)."""
    return {name: float(np.mean(jcts)) if (jcts := baseline_jcts(
                windows, n_nodes, gpus_per_node, name)).size else 0.0
            for name in names}


def _replay_jcts(states, traces) -> np.ndarray:
    """Pooled per-job JCTs (completed valid jobs) from replay end states."""
    sim = jax.tree.map(np.asarray, states.sim)
    tr = jax.tree.map(np.asarray, traces)
    finish = np.asarray(sim.finish, np.float64)
    done = tr.valid & np.isfinite(finish)
    return (finish[done] - np.asarray(tr.submit, np.float64)[done])


def jct_report(exp, windows: list[ArrayTrace] | None = None,
               max_steps: int | None = None,
               baselines: tuple[str, ...] = ("fifo", "sjf", "srtf",
                                             "tiresias"),
               include_random: bool = True,
               percentiles: tuple[float, ...] | None = None,
               backlog_gate: int = 0,
               stall_guard: bool = True,
               ) -> dict[str, Any]:
    """The full comparison table for an assembled Experiment: trained-policy
    greedy replay vs oracle baselines on identical windows.

    Returns {"policy": jct, "random": jct, <baseline>: jct, ...,
    "policy_completion": frac, "vs_tiresias": ratio} — ratio < 1.0 means the
    policy beats Tiresias (north-star #2, SURVEY.md §6). With
    ``percentiles`` (e.g. ``(50, 90, 99)``) the report also carries
    ``report["percentiles"][<row>]["p90"]`` tail-latency columns per
    scheduler (SURVEY.md §2 "avg/percentile JCT") — flat configs only (the
    hierarchical end state keeps per-pod tables, not a flat finish array).

    For hierarchical experiments (config 5) the policy schedules gangs
    within pods while the oracle baselines use the whole flat cluster —
    the baselines get strictly more placement freedom, so the comparison
    is conservative for the policy.
    """
    is_hier = isinstance(exp.env_params, HierParams)
    if percentiles is not None and is_hier:
        raise ValueError("percentiles are supported for flat configs")
    if windows is None:
        # the windows the experiment trained on (already validated/clamped
        # at build) — no re-ingest of the source trace
        windows, traces = exp.windows, exp.traces
    else:
        params = exp.env_params.pod_sim if is_hier else exp.env_params
        traces = env_lib.stack_traces(windows, params)

    report: dict[str, Any] = {}
    pcts: dict[str, dict[str, float]] = {}
    if backlog_gate:
        # saved artifacts from gated and ungated runs must be
        # distinguishable (ADVICE r3): record the gate next to the row
        report["backlog_gate"] = int(backlog_gate)
    if _preempt_slice(exp.env_params) is not None:
        # same distinguishability contract for the stall guard (VERDICT
        # r4 weak #6): whenever the guard CAN engage (preemptive action
        # space), record whether it did — guarded and unguarded numbers
        # are different schedulers
        report["stall_guard"] = bool(stall_guard)
    # the gate is part of the scheduler under evaluation (policy+FIFO
    # hybrid); the random control row stays pure random
    res, states = replay(exp.apply_fn, exp.train_state.params,
                         exp.env_params, traces, max_steps,
                         return_states=True, backlog_gate=backlog_gate,
                         stall_guard=stall_guard)
    report["policy"], report["policy_completion"] = pooled_avg_jct(res)
    report["policy_utilization"] = float(np.mean(np.asarray(res.utilization)))
    if percentiles is not None:
        # a truncated replay (max_steps cut) drops exactly the LONGEST
        # jobs, so its tail percentiles would read better than the
        # baselines' full-completion tails — same survivor-bias class
        # fairness_report guards against. No row rather than a wrong row.
        pcts["policy"] = (_pct_row(_replay_jcts(states, traces), percentiles)
                          if report["policy_completion"] >= 1.0 else {})
    if include_random:
        rnd, rnd_states = replay(exp.apply_fn, exp.train_state.params,
                                 exp.env_params, traces, max_steps,
                                 policy="random", key=jax.random.PRNGKey(1),
                                 return_states=True)
        report["random"], rnd_completion = pooled_avg_jct(rnd)
        if percentiles is not None:
            pcts["random"] = (_pct_row(_replay_jcts(rnd_states, traces),
                                       percentiles)
                              if rnd_completion >= 1.0 else {})
    for name in baselines:
        jcts = baseline_jcts(windows, exp.cfg.n_nodes,
                             exp.cfg.gpus_per_node, name)
        report[name] = float(np.mean(jcts)) if jcts.size else 0.0
        if percentiles is not None:
            pcts[name] = _pct_row(jcts, percentiles)
    if "tiresias" in report and report["tiresias"] > 0:
        report["vs_tiresias"] = report["policy"] / report["tiresias"]
    if percentiles is not None:
        report["percentiles"] = pcts
    return report


def full_trace_report(exp, max_jobs: int | None = None,
                      baselines: tuple[str, ...] = ("fifo", "sjf", "srtf",
                                                    "tiresias"),
                      max_steps_per_window: int | None = None,
                      include_random: bool = True,
                      percentiles: tuple[float, ...] | None = None,
                      env_params: EnvParams | None = None,
                      backlog_gate: int = 0,
                      stall_guard: bool = True,
                      drain_completions: int = 1,
                      faults=None,
                      ) -> dict[str, Any]:
    """The FULL-trace comparison table (``evaluate --full-trace``): policy
    avg-JCT via :func:`full_trace_replay` vs the baselines run by the
    native C++ engine (oracle fallback) over the exact same source trace —
    the apples-to-apples full-Philly comparison north-star #2 demands.
    ``include_random`` adds a masked-uniform-policy row through the same
    windowed-replay machinery (the learning-smoke yardstick: the trained
    policy must decisively beat it).

    ``env_params`` overrides the stitch-replay environment — in particular
    its ``sim.max_jobs`` stitch-window size. The policy nets are
    max_jobs-independent (observations are functions of the cluster and
    the queue view, not the job-table size), so a checkpoint trained at
    one window size can replay through a DEEPER stitched window, widening
    the backlog the stitcher holds between seams; the cluster shape and
    queue_len must still match the checkpoint.

    ``faults``: one GLOBAL-time fault/domain schedule the whole table
    runs under (``evaluate --full-trace --stitch-faults/--stitch-domain``)
    — the policy rows stitch through it window-by-window
    (:func:`full_trace_replay`), the baselines run the SAME unshifted
    schedule on the oracle's global clock, so the comparison stays
    apples-to-apples on the degraded cluster. Forces the Python-oracle
    baseline backend (the native engine has no fault model)."""
    eval_params = env_params or exp.env_params
    if isinstance(exp.env_params, HierParams) or \
            isinstance(eval_params, HierParams):
        raise ValueError("full-trace evaluation supports flat configs; "
                         "hierarchical pods replay per-window (jct_report)")
    if env_params is not None:
        # enforce the whole contract, not just sim geometry: time_scale /
        # obs_kind / reward bins are baked into the checkpointed policy's
        # observation semantics too — only the stitch window may differ
        normalized = dataclasses.replace(
            eval_params, sim=dataclasses.replace(
                eval_params.sim, max_jobs=exp.env_params.sim.max_jobs),
            horizon=exp.env_params.horizon)
        if normalized != exp.env_params:
            raise ValueError(
                "env_params may change the stitch window (sim.max_jobs) "
                "and horizon only; every other field is baked into the "
                "checkpointed policy's observation and action spaces")
    source = exp.source
    if max_jobs is not None and source.num_jobs > max_jobs:
        source = source.slice(0, max_jobs)
    pcts: dict[str, dict[str, float]] = {}
    out = full_trace_replay(exp.apply_fn, exp.train_state.params,
                            eval_params, source,
                            max_steps_per_window=max_steps_per_window,
                            backlog_gate=backlog_gate,
                            stall_guard=stall_guard,
                            drain_completions=drain_completions,
                            faults=faults)
    report: dict[str, Any] = {"policy": out["avg_jct"],
                              "n_jobs": out["n_jobs"],
                              "policy_windows": out["windows"]}
    if faults is not None:
        # a degraded-cluster table must never be confused with a clean
        # one (same distinguishability contract as backlog_gate below)
        report["faulty_cluster"] = True
    if backlog_gate:
        report["backlog_gate"] = int(backlog_gate)
    if _preempt_slice(eval_params) is not None:
        # see jct_report: mark guarded vs unguarded artifacts apart
        report["stall_guard"] = bool(stall_guard)
    if out["drain_completions"] != 1:
        # non-default stitch batching is part of the evaluated scheduler's
        # approximation — keep artifacts distinguishable (same contract as
        # backlog_gate / stall_guard markers). Record the EFFECTIVE
        # post-clamp value: a request clamped back to 1 IS the default
        # replay and must not be marked as a different scheduler
        report["drain_completions"] = int(out["drain_completions"])
    if percentiles is not None:
        # full_trace_replay asserts every job finished, so unlike the
        # per-window harness there is no truncation bias to guard
        pcts["policy"] = _pct_row(out["jct"], percentiles)
    if include_random:
        rnd = full_trace_replay(exp.apply_fn, exp.train_state.params,
                                eval_params, source,
                                max_steps_per_window=max_steps_per_window,
                                policy="random", key=jax.random.PRNGKey(1),
                                drain_completions=drain_completions,
                                faults=faults)
        report["random"] = rnd["avg_jct"]
        if percentiles is not None:
            pcts["random"] = _pct_row(rnd["jct"], percentiles)
    for name in baselines:
        sim = run_baseline(source, exp.cfg.n_nodes, exp.cfg.gpus_per_node,
                           name, faults=faults)
        report[name] = sim.avg_jct()
        if percentiles is not None:
            pcts[name] = _pct_row(sim.jcts(), percentiles)
    if report.get("tiresias"):
        report["vs_tiresias"] = report["policy"] / report["tiresias"]
    if percentiles is not None:
        report["percentiles"] = pcts
    return report


# ---- chaos evaluation matrix (ISSUE 6) --------------------------------------

# the canonical regime axis of ``evaluate --chaos``: clean control,
# uncorrelated background drains, correlated drain storms, stragglers
CHAOS_REGIMES = ("none", "sporadic", "storm", "straggler")


def _chaos_conservation(states, traces, env_params: EnvParams,
                        faults=None) -> dict:
    """The no-jobs-lost contract over a batch of final replay states:
    every node's ``free + allocated == capacity``, every RUNNING job holds
    exactly its gang, every non-RUNNING job holds nothing, and every valid
    job is in a legitimate lifecycle status — i.e. a drain KILLED jobs
    back to the queue rather than leaking them or their GPUs. Returns
    ``{"jobs_lost": int, "conserved": bool}``; the chaos and
    generalization matrices assert both.

    ``faults``: the batched schedule the replay ran under. A
    :class:`~.domains.DomainSchedule` carries per-node capacity [E, N] —
    the conservation target on a randomized-geometry cluster is the
    DRAWN capacity, not the static ``gpus_per_node``."""
    sim = jax.tree.map(np.asarray, states.sim)
    tr = jax.tree.map(np.asarray, traces)
    cap = getattr(faults, "capacity", None)
    expected = (env_params.sim.gpus_per_node if cap is None
                else np.asarray(cap))          # scalar or [E, N]
    node_ok = bool((sim.alloc.sum(axis=1) + sim.free == expected).all())
    alloc_j = sim.alloc.sum(axis=2)                       # [E, J]
    running = sim.status == RUNNING_STATUS
    run_ok = bool((alloc_j[running] == tr.gpus[running]).all())
    idle_ok = bool((alloc_j[~running] == 0).all())
    live = ((sim.status == NOT_ARRIVED_STATUS)
            | (sim.status == PENDING_STATUS) | running
            | (sim.status == DONE_STATUS))
    lost = int(tr.valid.sum() - (tr.valid & live).sum())
    return {"jobs_lost": lost,
            "conserved": node_ok and run_ok and idle_ok and lost == 0}


def chaos_report(exp, regimes: tuple[str, ...] = CHAOS_REGIMES,
                 baselines: tuple[str, ...] = ("sjf", "tiresias"),
                 max_steps: int | None = None, seed: int = 0,
                 bus=None, registry=None, tracer=None) -> dict[str, Any]:
    """The regime × scheduler chaos matrix (``evaluate --chaos``): replay
    the trained policy AND the oracle baselines over the experiment's
    windows under identical seeded fault schedules, one column per
    scheduler, one row per fault regime, with **degradation vs clean**
    (regime JCT / clean-regime JCT, per scheduler) as the headline —
    "how much does each scheduler's JCT rot when the cluster starts
    failing" is the robustness question this PR makes measurable.

    The clean control ("none") is always evaluated (prepended when not
    requested) because degradation is relative to it. Policy rows replay
    the jitted env under batched per-env :class:`~.sim.faults.
    FaultSchedule` data; baseline rows run the SAME per-window schedules
    through the oracle event loop (``run_baseline(faults=...)``), so the
    comparison is apples-to-apples per cell.

    Every regime row enforces the no-jobs-lost conservation contract
    (:func:`_chaos_conservation`) — a fault may delay work, never leak
    it. Reproducibility tuple: ``(seed, regime name/params, window
    batch)``; env ``e`` draws schedule ``(seed, e)``.

    ``bus`` (:class:`obs.EventBus`) emits one ``env_fault`` event per
    matrix cell plus per-regime schedule stats; ``registry``
    (:class:`obs.Registry`) gains ``chaos_<regime>_<scheduler>_*``
    gauges — the chaos story ``obs.report`` renders. ``tracer``
    (:class:`obs.Tracer`, ``evaluate --trace-spans``) records each
    regime row as a ``chaos_regime`` span nesting the ``policy_replay``
    and per-``baseline`` extents."""
    from .obs.trace import NULL_TRACER
    from .sim.faults import (fault_horizon, resolve_regime,
                             sample_fault_schedule, schedule_stats,
                             stack_fault_schedules)
    if tracer is None:
        tracer = NULL_TRACER
    if isinstance(exp.env_params, HierParams):
        raise ValueError("chaos evaluation supports flat configs (the "
                         "hierarchical env has no fault-process support)")
    env_params = exp.env_params
    windows, traces = exp.windows, exp.traces
    n_nodes, g = exp.cfg.n_nodes, exp.cfg.gpus_per_node
    horizon_s = fault_horizon(windows)
    regimes = list(dict.fromkeys(["none", *regimes]))
    report: dict[str, Any] = {
        "chaos_seed": int(seed), "fault_horizon_s": float(horizon_s),
        "chaos_regimes": list(regimes), "jobs_lost": 0,
        "regimes": {}, "fault_stats": {}}
    for name in regimes:
        with tracer.span("chaos_regime", regime=name):
            regime = resolve_regime(name)
            host = [sample_fault_schedule(n_nodes, regime, (seed, e),
                                          horizon_s)
                    for e in range(len(windows))]
            batched = stack_fault_schedules(host)
            report["fault_stats"][name] = schedule_stats(batched)
            with tracer.span("policy_replay"):
                res, states = replay(exp.apply_fn,
                                     exp.train_state.params,
                                     env_params, traces, max_steps,
                                     return_states=True, faults=batched)
            cons = _chaos_conservation(states, traces, env_params)
            if not cons["conserved"]:
                raise AssertionError(
                    f"conservation violated under regime {name!r}: "
                    f"{cons} — a fault schedule must delay jobs, never "
                    f"leak them or their GPUs")
            report["jobs_lost"] += cons["jobs_lost"]
            jct, completion = pooled_avg_jct(res)
            rows: dict[str, Any] = {
                "policy": {"avg_jct": jct, "completion": completion}}
            for bname in baselines:
                jcts, n_valid = [], 0
                with tracer.span("baseline", scheduler=bname):
                    for w, fs in zip(windows, host):
                        bl = run_baseline(w, n_nodes, g, bname,
                                          faults=fs)
                        jcts.append(bl.jcts())
                        n_valid += w.num_jobs
                pooled = np.concatenate(jcts) if jcts else np.zeros(0)
                rows[bname] = {
                    "avg_jct": (float(pooled.mean()) if pooled.size
                                else 0.0),
                    "completion": float(pooled.size / max(n_valid, 1))}
            report["regimes"][name] = rows
    clean = report["regimes"]["none"]
    for name, rows in report["regimes"].items():
        for sched, row in rows.items():
            base = clean[sched]["avg_jct"]
            row["degradation"] = (row["avg_jct"] / base
                                  if base and np.isfinite(base) else None)
    for name, rows in report["regimes"].items():
        for sched, row in rows.items():
            if bus is not None:
                bus.emit("env_fault", regime=name, scheduler=sched,
                         avg_jct=round(row["avg_jct"], 3),
                         completion=round(row["completion"], 4),
                         degradation=(round(row["degradation"], 4)
                                      if row["degradation"] is not None
                                      else None),
                         chaos_seed=int(seed),
                         **{f"fault_{k}": v for k, v in
                            report["fault_stats"][name].items()})
            if registry is not None:
                stem = f"chaos_{name}_{sched}"
                registry.gauge(f"{stem}_avg_jct").set(row["avg_jct"])
                registry.gauge(f"{stem}_completion").set(
                    row["completion"])
                if row["degradation"] is not None:
                    registry.gauge(f"{stem}_degradation").set(
                        row["degradation"])
    return report


def format_chaos(report: dict[str, Any]) -> str:
    """Human-readable chaos matrix: one row per regime, one column per
    scheduler, each cell ``avg JCT [completion] ×degradation``."""
    regimes = list(report["regimes"])
    scheds = list(next(iter(report["regimes"].values())))
    width = max(len("regime"), *(len(r) for r in regimes))
    cell_w = 24
    lines = [f"chaos matrix (seed {report['chaos_seed']}, fault horizon "
             f"{report['fault_horizon_s']:.0f}s) — "
             f"avg JCT s [completion] ×degradation-vs-clean:",
             f"{'regime':<{width}}  " +
             "  ".join(f"{s:<{cell_w}}" for s in scheds)]
    for name in regimes:
        cells = []
        for s in scheds:
            row = report["regimes"][name][s]
            deg = (f"×{row['degradation']:.2f}"
                   if row["degradation"] is not None else "×—")
            cells.append(f"{row['avg_jct']:>8.1f} "
                         f"[{row['completion']:>4.0%}] {deg:<7}")
        lines.append(f"{name:<{width}}  " +
                     "  ".join(f"{c:<{cell_w}}" for c in cells))
    lines.append(f"jobs lost across the matrix: {report['jobs_lost']} "
                 f"(conservation contract: must be 0)")
    return "\n".join(lines)


# ---- generalization matrix (ISSUE 14) ---------------------------------------

# the canonical eval axis of ``evaluate --matrix``: fixed-cluster control,
# mild load/duration jitter, heterogeneous hardware, sustained 1.6×
# overload — the measured weak spot (BASELINE.md) the matrix tracks as a
# number next to JCT
MATRIX_REGIMES = ("none", "baseline", "hetero", "overload")


@functools.partial(jax.jit, static_argnums=(0, 1, 2))
def _matrix_cell(apply_fn, env_params, max_steps, net_params, traces,
                 faults):
    """One jitted matrix cell: greedy policy replay over a domain-schedule
    batch. Module-level with static (apply_fn, env_params, max_steps) so
    every column of a policy row hits ONE compile cache entry — the
    zero-retrace-across-domains contract (same recipe as FAULT_REGIMES:
    every regime's :class:`~.domains.DomainSchedule` has identical
    shapes/treedef, so only the data changes between cells)."""
    return replay(apply_fn, net_params, env_params, traces, max_steps,
                  return_states=True, faults=faults)


def matrix_report(exp, regimes: tuple[str, ...] = MATRIX_REGIMES,
                  baselines: tuple[str, ...] = ("sjf", "tiresias"),
                  policies: dict[str, tuple] | None = None,
                  max_steps: int | None = None, seed: int = 0,
                  bus=None, registry=None, alarms=None) -> dict[str, Any]:
    """The train-regime × eval-regime generalization matrix
    (``evaluate --matrix``): replay one or more trained policies AND the
    oracle baselines under identical ``(seed, env)``-seeded domain draws
    — randomized cluster geometry, heterogeneous speeds, and arrival
    regimes up to sustained overload — one row per scheduler, one column
    per eval regime, with **degradation vs the fixed-cluster control**
    (regime JCT / 'none' JCT, per scheduler) as the headline. "Does the
    policy trained on one cluster still schedule a cluster it never saw"
    is the generalization question this matrix makes measurable; the
    ``overload`` column turns the measured 1.6×-overload weakness
    (BASELINE.md) into a tracked number next to JCT.

    Every cell in a column shares the SAME windows and the same batched
    :class:`~.domains.DomainSchedule` (env ``e`` draws ``(seed, e)``
    under the column's spec; windows are generated against each draw's
    ACTUAL capacity by ``experiment.make_domain_windows``), so the
    comparison is apples-to-apples per column. The fixed-cluster control
    ("none") is always evaluated (prepended when not requested) because
    degradation is relative to it.

    ``policies``: ``{row_name: (apply_fn, net_params, env_params)}`` —
    extra rows for checkpoints trained under other regimes
    (``evaluate --matrix-ckpt``); default is the experiment's own policy.
    Per-row ``env_params`` may differ in observation channels only
    (a domain-sighted checkpoint sees geometry/health, a blind one does
    not); the sim geometry must match — every row replays the same
    cluster draws.

    Every cell enforces the no-jobs-lost conservation contract against
    the DRAWN per-node capacity (:func:`_chaos_conservation`).
    Reproducibility tuple: ``(seed, regime, n_nodes, gpus_per_node,
    window config)``.

    ``bus`` (:class:`obs.EventBus`) emits one ``domain_cell`` event per
    cell plus per-regime draw stats; ``registry`` gains
    ``matrix_<regime>_<scheduler>_*`` gauges. ``alarms``
    (:class:`obs.telemetry.Alarms`, already entered) wraps each jitted
    cell dispatch: after the warmup cell, a recompile or implicit
    transfer in any cell is an alarm event — each ADDITIONAL policy row's
    first cell legitimately compiles its own program (different
    observation space) and is granted ``expect_recompile`` amnesty."""
    from .domains import (domain_schedule, domain_stats, resolve_domain,
                          sample_env_domains, stack_domain_schedules,
                          validate_domain_schedule)
    from .experiment import make_domain_windows
    if isinstance(exp.env_params, HierParams):
        raise ValueError("the generalization matrix supports flat configs "
                         "(domain schedules carry per-node capacity "
                         "through the flat sim path only)")
    cfg = exp.cfg
    n_nodes, g = cfg.n_nodes, cfg.gpus_per_node
    if policies is None:
        policies = {"policy": (exp.apply_fn, exp.train_state.params,
                               exp.env_params)}
    for pname, (_, _, ep) in policies.items():
        if isinstance(ep, HierParams) or ep.sim != exp.env_params.sim:
            raise ValueError(
                f"matrix row {pname!r} has a different sim geometry than "
                f"the experiment; every row must replay the same cluster "
                f"draws (rows may differ in observation channels only)")
    regimes = list(dict.fromkeys(["none", *regimes]))
    # matrix draws and windows are governed by the MATRIX seed, not the
    # training seed — the repro tuple records it
    mcfg = dataclasses.replace(cfg, seed=int(seed))

    report: dict[str, Any] = {
        "matrix_seed": int(seed), "matrix_regimes": list(regimes),
        "jobs_lost": 0, "cells": {}, "domain_stats": {}}
    # one column's data is built ONCE and shared by every row
    columns: dict[str, tuple] = {}
    for rname in regimes:
        spec = resolve_domain(rname)
        draws = sample_env_domains(spec, n_nodes, g, seed, cfg.n_envs)
        windows = make_domain_windows(mcfg, draws)
        host = [validate_domain_schedule(n_nodes, g, domain_schedule(d))
                for d in draws]
        batched = stack_domain_schedules(host)
        traces = env_lib.stack_traces(windows, exp.env_params)
        columns[rname] = (windows, host, batched, traces)
        stats = [domain_stats(d) for d in draws]
        report["domain_stats"][rname] = {
            "mean_total_gpus": float(np.mean([s["total_gpus"]
                                              for s in stats])),
            "envs_with_nodes_off": int(sum(s["n_nodes_off"] > 0
                                           for s in stats)),
            "envs_hetero": int(sum(s["n_hetero"] > 0 for s in stats)),
            "max_slowdown": float(max(s["max_slowdown"] for s in stats)),
            "mean_load": float(np.mean([s["load"] for s in stats])),
        }
        report["cells"][rname] = {}

    dispatch = 0
    for pi, (pname, (apply_fn, params, ep)) in enumerate(policies.items()):
        params = jax.device_put(params)
        for ci, rname in enumerate(regimes):
            _, _, batched, traces = columns[rname]
            if alarms is not None and ci == 0 and pi > 0:
                alarms.expect_recompile(
                    f"matrix row {pname!r}: first cell compiles its own "
                    f"replay program (different observation space)")
            ctx = (alarms.dispatch(dispatch) if alarms is not None
                   else contextlib.nullcontext())
            with ctx:
                res, states = _matrix_cell(apply_fn, ep, max_steps,
                                           params, traces, batched)
                jax.block_until_ready(res.avg_jct)
            dispatch += 1
            cons = _chaos_conservation(states, traces, ep, faults=batched)
            if not cons["conserved"]:
                raise AssertionError(
                    f"conservation violated in matrix cell "
                    f"({pname!r}, {rname!r}): {cons} — a domain draw must "
                    f"shrink or slow the cluster, never leak jobs or "
                    f"GPUs")
            report["jobs_lost"] += cons["jobs_lost"]
            jct, completion = pooled_avg_jct(res)
            report["cells"][rname][pname] = {"avg_jct": jct,
                                             "completion": completion}
    for bname in baselines:
        for rname in regimes:
            windows, host, _, _ = columns[rname]
            jcts, n_valid = [], 0
            for w, fs in zip(windows, host):
                bl = run_baseline(w, n_nodes, g, bname, faults=fs)
                jcts.append(bl.jcts())
                n_valid += w.num_jobs
            pooled = np.concatenate(jcts) if jcts else np.zeros(0)
            report["cells"][rname][bname] = {
                "avg_jct": (float(pooled.mean()) if pooled.size else 0.0),
                "completion": float(pooled.size / max(n_valid, 1))}

    clean = report["cells"]["none"]
    for rname, cols in report["cells"].items():
        for sched, row in cols.items():
            base = clean[sched]["avg_jct"]
            row["degradation"] = (row["avg_jct"] / base
                                  if base and np.isfinite(base) else None)
    for rname, cols in report["cells"].items():
        for sched, row in cols.items():
            if bus is not None:
                bus.emit("domain_cell", regime=rname, scheduler=sched,
                         avg_jct=round(row["avg_jct"], 3),
                         completion=round(row["completion"], 4),
                         degradation=(round(row["degradation"], 4)
                                      if row["degradation"] is not None
                                      else None),
                         matrix_seed=int(seed),
                         **{f"domain_{k}": v for k, v in
                            report["domain_stats"][rname].items()})
            if registry is not None:
                stem = f"matrix_{rname}_{sched}"
                registry.gauge(f"{stem}_avg_jct").set(row["avg_jct"])
                registry.gauge(f"{stem}_completion").set(
                    row["completion"])
                if row["degradation"] is not None:
                    registry.gauge(f"{stem}_degradation").set(
                        row["degradation"])
    return report


def format_matrix(report: dict[str, Any]) -> str:
    """Human-readable generalization matrix: one row per eval regime, one
    column per scheduler, each cell ``avg JCT [completion]
    ×degradation-vs-none``."""
    regimes = list(report["cells"])
    scheds = list(next(iter(report["cells"].values())))
    width = max(len("eval regime"), *(len(r) for r in regimes))
    cell_w = 24
    lines = [f"generalization matrix (seed {report['matrix_seed']}) — "
             f"avg JCT s [completion] ×degradation-vs-none:",
             f"{'eval regime':<{width}}  " +
             "  ".join(f"{s:<{cell_w}}" for s in scheds)]
    for name in regimes:
        cells = []
        for s in scheds:
            row = report["cells"][name][s]
            deg = (f"×{row['degradation']:.2f}"
                   if row["degradation"] is not None else "×—")
            cells.append(f"{row['avg_jct']:>8.1f} "
                         f"[{row['completion']:>4.0%}] {deg:<7}")
        lines.append(f"{name:<{width}}  " +
                     "  ".join(f"{c:<{cell_w}}" for c in cells))
    for name in regimes:
        st = report["domain_stats"][name]
        lines.append(f"  {name}: ~{st['mean_total_gpus']:.1f} GPUs/env, "
                     f"{st['envs_with_nodes_off']} envs with nodes off, "
                     f"{st['envs_hetero']} hetero, "
                     f"max slowdown ×{st['max_slowdown']:.1f}, "
                     f"load {st['mean_load']:.2f}")
    lines.append(f"jobs lost across the matrix: {report['jobs_lost']} "
                 f"(conservation contract: must be 0)")
    return "\n".join(lines)


def jain_index(xs: np.ndarray) -> float:
    """Jain's fairness index over per-tenant values: (Σx)²/(n·Σx²) — 1.0
    means perfectly equal, 1/n means all dispersion on one tenant.
    ``fairness_report`` feeds per-tenant mean RAW JCT (so a tenant whose
    jobs are intrinsically long reads as worse-treated; use a slowdown
    transform upstream if that distinction matters to you)."""
    xs = np.asarray(xs, np.float64)
    xs = xs[np.isfinite(xs) & (xs > 0)]
    if xs.size == 0:
        return float("nan")
    return float(xs.sum() ** 2 / (xs.size * np.square(xs).sum()))


def _pool_tenant_jct(finish: np.ndarray, submit: np.ndarray,
                     tenant: np.ndarray, done: np.ndarray,
                     n_tenants: int, sums: np.ndarray, counts: np.ndarray,
                     ) -> None:
    # one bincount pass, not a per-tenant mask loop: real CSVs make
    # n_tenants the distinct-user count (thousands). Subtract under the
    # mask only — padding rows are inf-inf = NaN
    t = tenant[done]
    sums += np.bincount(t, weights=finish[done] - submit[done],
                        minlength=n_tenants)
    counts += np.bincount(t, minlength=n_tenants)


def fairness_report(exp, windows: list[ArrayTrace] | None = None,
                    max_steps: int | None = None,
                    baselines: tuple[str, ...] = ("fifo", "sjf", "srtf",
                                                  "tiresias"),
                    ) -> dict[str, Any]:
    """Multi-tenant fairness table (config 3, SURVEY.md §0 "multi-tenant
    fairness reward"): per-tenant avg JCT under the trained policy vs the
    oracle baselines on identical windows, summarized by Jain's index over
    per-tenant means (1.0 = perfectly even treatment) next to each
    scheduler's plain avg JCT — the quantitative form of "did the fairness
    reward buy evener tenants without wrecking JCT".

    Returns ``{"<name>": {"avg_jct": .., "jain": ..,
    "tenant_avg_jct": [..]}, ...}`` with ``policy`` as one of the rows."""
    if isinstance(exp.env_params, HierParams):
        raise ValueError("fairness_report supports flat configs (tenant "
                         "ids live in the flat sim's trace)")
    if windows is None:
        windows, traces = exp.windows, exp.traces
    else:
        traces = env_lib.stack_traces(windows, exp.env_params)
    # pool over every tenant id actually present, not just
    # cfg.n_tenants bins: a real PAI CSV maps each distinct user to a
    # dense id unbounded by the config, and silently dropping tenants
    # >= n_tenants would skew avg_jct/Jain/completion for every row
    n_tenants = max(int(exp.cfg.n_tenants), 1,
                    1 + max((int(np.asarray(w.tenant)[w.valid].max())
                             for w in windows if w.valid.any()),
                            default=0))

    out: dict[str, Any] = {}
    _res, states = replay(exp.apply_fn, exp.train_state.params,
                          exp.env_params, traces, max_steps,
                          return_states=True)
    sums = np.zeros(n_tenants)
    counts = np.zeros(n_tenants, np.int64)
    sim = jax.tree.map(np.asarray, states.sim)
    tr = jax.tree.map(np.asarray, traces)
    for e in range(sim.finish.shape[0]):
        done = tr.valid[e] & np.isfinite(sim.finish[e])
        _pool_tenant_jct(sim.finish[e], tr.submit[e], tr.tenant[e], done,
                         n_tenants, sums, counts)
    per_tenant = np.where(counts > 0, sums / np.maximum(counts, 1), np.nan)
    n_valid = int(sum(w.num_jobs for w in windows))
    out["policy"] = {
        # NaN (not 0.0) when nothing completed, so a truncated replay
        # cannot sort itself to the top of the table; completion surfaces
        # the survivor bias a max_steps cut introduces (the baselines
        # always run to completion)
        "avg_jct": (float(sums.sum() / counts.sum()) if counts.sum()
                    else float("nan")),
        "jain": jain_index(per_tenant),
        "completion": float(counts.sum() / max(n_valid, 1)),
        "tenant_avg_jct": [round(float(x), 1) for x in per_tenant]}

    for name in baselines:
        sums = np.zeros(n_tenants)
        counts = np.zeros(n_tenants, np.int64)
        for w in windows:
            bl = run_baseline(w, exp.cfg.n_nodes, exp.cfg.gpus_per_node,
                              name)
            done = w.valid & np.isfinite(np.asarray(bl.finish, np.float64))
            _pool_tenant_jct(np.asarray(bl.finish, np.float64),
                             np.asarray(w.submit, np.float64),
                             np.asarray(w.tenant), done, n_tenants,
                             sums, counts)
        per_tenant = np.where(counts > 0, sums / np.maximum(counts, 1),
                              np.nan)
        out[name] = {
            "avg_jct": (float(sums.sum() / counts.sum()) if counts.sum()
                        else float("nan")),
            "jain": jain_index(per_tenant),
            "completion": float(counts.sum() / max(n_valid, 1)),
            "tenant_avg_jct": [round(float(x), 1) for x in per_tenant]}
    return out


def format_fairness(report: dict[str, Any]) -> str:
    width = max(len("scheduler"), *(len(k) for k in report))
    lines = [f"{'scheduler':<{width}}  avg JCT (s)  Jain(tenant JCT)  done",
             f"{'-' * width}  -----------  ----------------  ----"]
    order = sorted(report.items(),
                   key=lambda kv: (np.isnan(kv[1]["avg_jct"]),
                                   kv[1]["avg_jct"]))
    for k, v in order:
        lines.append(f"{k:<{width}}  {v['avg_jct']:>11.1f}  "
                     f"{v['jain']:>16.3f}  {v['completion']:>4.0%}")
    return "\n".join(lines)


def format_report(report: dict[str, Any]) -> str:
    """Human-readable JCT table (the BASELINE.md-style comparison)."""
    rows = [(k, v) for k, v in report.items()
            if isinstance(v, float) and k not in
            ("vs_tiresias", "policy_completion", "policy_utilization")]
    rows.sort(key=lambda kv: kv[1])
    width = max(len("scheduler"), *(len(k) for k, _ in rows))
    lines = [f"{'scheduler':<{width}}  avg JCT (s)",
             f"{'-' * width}  -----------"]
    for k, v in rows:
        lines.append(f"{k:<{width}}  {v:>11.1f}")
    if "percentiles" in report:
        cols = sorted({c for row in report["percentiles"].values()
                       for c in row},
                      key=lambda c: float(c[1:]))
        lines.append(f"{'':<{width}}  " +
                     "  ".join(f"{c:>9}" for c in cols))
        for k, _ in rows:
            row = report["percentiles"].get(k, {})
            lines.append(f"{k:<{width}}  " + "  ".join(
                f"{row[c]:>9.1f}" if c in row else f"{'—':>9}"
                for c in cols))
    if "vs_tiresias" in report:
        lines.append(f"policy/tiresias ratio: {report['vs_tiresias']:.3f} "
                     f"(<1 beats Tiresias)")
    if "policy_completion" in report:
        lines.append(f"policy completion: {report['policy_completion']:.1%}")
    return "\n".join(lines)
