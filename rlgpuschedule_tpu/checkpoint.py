"""Checkpoint / resume (L6 aux): Orbax persistence of training state.

Capability parity: SURVEY.md §5 "Checkpoint / resume" — the reference's
torch ``state_dict`` save/load becomes Orbax checkpointing of the
Flax/Optax ``TrainState``. Required by PBT (exploit copies a member's
weights, SURVEY.md §2 "PBT controller") and by failure recovery
(checkpoint-restart is the rebuild's recovery story, SURVEY.md §5
"Failure detection").

Sharding-aware by construction: Orbax records each array leaf's
``jax.sharding`` on save, and we restore against an abstract pytree built
from a live template state, so mesh-placed params round-trip onto the
same mesh layout without a host gather.

Layout per step: ``state/`` (params, opt_state, step, key — arrays) +
``meta/`` (JSON scalars: hyperparams, fitness — what PBT reads/writes).
"""
from __future__ import annotations

import sys
from typing import Any

import jax
import jax.numpy as jnp
import orbax.checkpoint as ocp
from flax.training.train_state import TrainState


class CheckpointRestoreError(RuntimeError):
    """Every retained checkpoint step failed to restore (corruption /
    truncation across the whole rotation window)."""


# module-level jit: a fresh `jax.jit(lambda ...)` per restore would defeat
# the jit cache and recompile the copy program on every rollback
# (jsan recompile-hazard, PR 3 first-run finding)
_fresh_copy_jit = jax.jit(lambda t: jax.tree.map(jnp.copy, t))


def _fresh_copy(tree: Any) -> Any:
    """Copy every restored array into a fresh device buffer. Orbax-restored
    buffers must NOT be donated back into a jitted step (donate_argnums):
    on the multi-device CPU backend that corrupts the heap (the seed's
    restore-then-run resume tests segfaulted the whole suite). One jitted
    copy decouples the training state from the restore machinery's
    buffers; sharding is preserved (copy is elementwise)."""
    return _fresh_copy_jit(tree)


def _state_tree(state: TrainState, key: jax.Array | None,
                extra: Any | None) -> dict:
    """TrainState holds non-serializable leaves (apply_fn, tx); persist only
    the array pytrees + step — the torch-state_dict analogue. ``extra`` is
    any additional array pytree (the Experiment checkpoints its rollout
    carry here so a resumed run replays the uninterrupted trajectory)."""
    tree: dict[str, Any] = {
        "step": state.step,
        "params": state.params,
        "opt_state": state.opt_state,
    }
    if key is not None:
        tree["key"] = key
    if extra is not None:
        tree["extra"] = extra
    return tree


class Checkpointer:
    """Rotating checkpoint store for one training run (or one PBT member).

    >>> ckpt = Checkpointer(dir, max_to_keep=3)
    >>> ckpt.save(step, train_state, key=rollout_key, meta={"lr": 3e-4})
    >>> state, key, meta = ckpt.restore(train_state, key)
    """

    def __init__(self, directory: str, max_to_keep: int | None = 3):
        self._mngr = ocp.CheckpointManager(
            directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True))
        self.last_restored_step: int | None = None

    @property
    def directory(self) -> str:
        return str(self._mngr.directory)

    def all_steps(self) -> list[int]:
        return sorted(self._mngr.all_steps())

    def latest_step(self) -> int | None:
        return self._mngr.latest_step()

    def save(self, step: int, state: TrainState,
             key: jax.Array | None = None, extra: Any | None = None,
             meta: dict | None = None, force: bool = False) -> bool:
        """Persist checkpoint ``step``. ``meta`` is a flat dict of JSON-able
        scalars (PBT stores hyperparams + fitness here); ``extra`` any array
        pytree. ``force=True`` overwrites an existing checkpoint at the same
        step (Orbax otherwise refuses the duplicate — needed when PBT
        exploit copies weights without a train step). Returns False when the
        save was skipped because the step already exists.

        A forced overwrite is delete-then-save (Orbax cannot swap a step in
        place), so it runs synchronously to keep the no-copy window as small
        as one save; a crash inside that window falls back to the previous
        retained step. Keep ``max_to_keep >= 2`` if you force-overwrite your
        only step."""
        if force:
            # an in-flight async save of the same step is invisible to
            # all_steps() until finalized — settle it first so force can't
            # silently degrade to a skipped save
            self._mngr.wait_until_finished()
            if step in self._mngr.all_steps():
                # Orbax refuses duplicate steps outright (its ``force`` only
                # bypasses save-interval policy); overwrite = delete + save
                self._mngr.delete(step)
        try:
            saved = self._mngr.save(
                step,
                args=ocp.args.Composite(
                    state=ocp.args.StandardSave(_state_tree(state, key, extra)),
                    meta=ocp.args.JsonSave(dict(meta or {}))),
                force=force)
        except ocp.checkpoint_manager.StepAlreadyExistsError:
            return False
        if force:
            self._mngr.wait_until_finished()
        return bool(saved)

    def restore(self, template_state: TrainState,
                template_key: jax.Array | None = None,
                template_extra: Any | None = None,
                step: int | None = None, fallback: bool = True,
                ) -> tuple[TrainState, jax.Array | None, Any, dict]:
        """Restore into the shape/dtype/sharding of ``template_state`` (a
        live state from the same model/optimizer build — its values are
        ignored). Pass ``template_key``/``template_extra`` iff they were
        saved. Returns (state, key-or-None, extra-or-None, meta).

        Integrity fallback: restoring the LATEST step (``step=None``)
        verifies the step actually restores; a step whose files are
        truncated/corrupted (or a partial dir left by a crash inside the
        force-overwrite delete+save window) is skipped with a visible
        stderr warning and the previous retained step is restored instead.
        Only when EVERY retained step fails does this raise
        :class:`CheckpointRestoreError`. An explicit ``step`` (or
        ``fallback=False``) restores exactly that step and re-raises its
        failure. ``self.last_restored_step`` records which step won.

        The returned arrays live in fresh buffers (see :func:`_fresh_copy`)
        so callers may hand them straight to a donating jitted step."""
        if step is not None:
            candidates = [step]
        else:
            candidates = sorted(self._mngr.all_steps(), reverse=True)
        if not candidates:
            raise FileNotFoundError(
                f"no checkpoint found under {self.directory}")
        template = _state_tree(template_state, template_key, template_extra)
        abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, template)
        restored = None
        errors: list[tuple[int, Exception]] = []
        for i, s in enumerate(candidates):
            try:
                restored = self._mngr.restore(
                    s,
                    args=ocp.args.Composite(
                        state=ocp.args.StandardRestore(abstract),
                        meta=ocp.args.JsonRestore()))
                self.last_restored_step = s
                break
            except Exception as e:   # orbax surfaces corruption as
                errors.append((s, e))  # assorted exception types
                if step is not None or not fallback:
                    raise
                if i + 1 < len(candidates):
                    print(f"checkpoint: step {s} failed to restore "
                          f"({type(e).__name__}: {str(e)[:200]}); "
                          f"falling back to step {candidates[i + 1]}",
                          file=sys.stderr, flush=True)
        if restored is None:
            raise CheckpointRestoreError(
                f"all {len(candidates)} retained checkpoint steps under "
                f"{self.directory} failed to restore: "
                + "; ".join(f"step {s}: {type(e).__name__}"
                            for s, e in errors)) from errors[-1][1]
        tree = _fresh_copy(restored["state"])
        # TrainState is a flax struct (.replace); population MemberState is
        # a NamedTuple (._replace) — both checkpoint through the same path
        rep = getattr(template_state, "replace", None) or \
            template_state._replace
        state = rep(step=tree["step"], params=tree["params"],
                    opt_state=tree["opt_state"])
        return state, tree.get("key"), tree.get("extra"), dict(
            restored["meta"] or {})

    def read_meta(self, step: int | None = None) -> dict:
        """Read a checkpoint's JSON meta without restoring its arrays
        (e.g. the best-checkpoint bar a resumed --keep-best run recovers)."""
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(
                f"no checkpoint found under {self.directory}")
        restored = self._mngr.restore(
            step, args=ocp.args.Composite(meta=ocp.args.JsonRestore()))
        return dict(restored["meta"] or {})

    def wait(self) -> None:
        """Block until async saves are durable (call before reading the
        files from another process, e.g. a PBT exploit copy)."""
        self._mngr.wait_until_finished()

    def close(self) -> None:
        self._mngr.wait_until_finished()
        self._mngr.close()

    def __enter__(self) -> "Checkpointer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
