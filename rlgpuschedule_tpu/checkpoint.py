"""Checkpoint / resume (L6 aux): Orbax persistence of training state.

Capability parity: SURVEY.md §5 "Checkpoint / resume" — the reference's
torch ``state_dict`` save/load becomes Orbax checkpointing of the
Flax/Optax ``TrainState``. Required by PBT (exploit copies a member's
weights, SURVEY.md §2 "PBT controller") and by failure recovery
(checkpoint-restart is the rebuild's recovery story, SURVEY.md §5
"Failure detection").

Sharding-aware by construction: Orbax records each array leaf's
``jax.sharding`` on save, and we restore against an abstract pytree built
from a live template state, so mesh-placed params round-trip onto the
same mesh layout without a host gather.

Layout per step: ``state/`` (params, opt_state, step, key — arrays) +
``meta/`` (JSON scalars: hyperparams, fitness — what PBT reads/writes),
plus a ``.crc/<step>.json`` sidecar (crc32 per payload file) so restore
can reject a torn/truncated step with a cheap read instead of a full
failed deserialization.

Elastic recovery (shrink-to-fit): :meth:`Checkpointer.elastic_restore`
restores a checkpoint written at world size N onto a SMALLER surviving
topology — replicated state (params, optimizer moments) is world-size
independent and restores unchanged; env-batched ``extra`` payloads (the
rollout carry) keep only the surviving data shards' row blocks, decided
per-leaf by the partition-rule table
(``parallel.sharding.shrink_env_rows_by_rule``); and the update geometry is
re-validated against the shrunk global batch up front
(:func:`validate_shrunk_geometry`), so an untileable shrink fails with
a clear error instead of a shape error mid-step.
"""
from __future__ import annotations

import json
import os
import sys
import zlib
from typing import Any

import jax
import jax.numpy as jnp
import orbax.checkpoint as ocp
from flax.training.train_state import TrainState


class CheckpointRestoreError(RuntimeError):
    """Every retained checkpoint step failed to restore (corruption /
    truncation across the whole rotation window)."""


class CheckpointChecksumError(RuntimeError):
    """A step's crc32 sidecar disagrees with its on-disk payload (torn
    write / truncation, caught by the cheap pre-check)."""


class ElasticRestoreError(RuntimeError):
    """A shrink-to-fit restore cannot produce a runnable configuration at
    the surviving world size (untileable update geometry / batch)."""


def _sidecar_path(directory: str, step: int) -> str:
    # outside the step dir: Orbax owns the step dir's contents, and a
    # foreign file inside it would be deleted with the step anyway —
    # .crc/ is pruned by Checkpointer.wait() instead
    return os.path.join(directory, ".crc", f"{step}.json")


def _step_payload_files(directory: str, step: int) -> list[str]:
    """Every file of checkpoint ``step``, as step-dir-relative paths
    (sorted for a stable sidecar)."""
    step_dir = os.path.join(directory, str(step))
    out = []
    for root, _dirs, files in os.walk(step_dir):
        for f in files:
            out.append(os.path.relpath(os.path.join(root, f), step_dir))
    return sorted(out)


def _crc32_file(path: str) -> int:
    crc = 0
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            crc = zlib.crc32(chunk, crc)
    return crc


def write_checksum_sidecar(directory: str, step: int) -> dict[str, int]:
    """(Re)compute ``{relpath: crc32}`` over checkpoint ``step``'s files
    and atomically write the ``.crc/<step>.json`` sidecar. Called by
    :meth:`Checkpointer.wait` once a save is durable (checksumming an
    in-flight async save would record a torn view — exactly what the
    sidecar exists to catch)."""
    sums = {rel: _crc32_file(os.path.join(directory, str(step), rel))
            for rel in _step_payload_files(directory, step)}
    path = _sidecar_path(directory, step)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(sums, f)
    os.replace(tmp, path)
    return sums


def validate_shrunk_geometry(n_epochs: int, n_minibatches: int,
                             minibatch_size: int | None, n_steps: int,
                             n_envs: int, old_n_envs: int | None = None
                             ) -> tuple[int, int, int]:
    """Re-validate the update geometry against a SHRUNK global batch
    (``n_steps × n_envs``), translating the tiling failure into
    :class:`ElasticRestoreError` with the shrink named — the fail-fast
    gate a shrink-to-fit restart runs BEFORE compiling anything, so an
    untileable surviving world dies with a clear error instead of a
    shape error mid-step. Returns the resolved geometry triple."""
    from rlgpuschedule_tpu.algos.update import resolve_geometry
    try:
        return resolve_geometry(n_epochs, n_minibatches, minibatch_size,
                                n_steps * n_envs)
    except ValueError as e:
        was = (f" (was {n_steps * old_n_envs} before the shrink)"
               if old_n_envs is not None else "")
        raise ElasticRestoreError(
            f"shrink-to-fit: surviving global batch n_steps*n_envs = "
            f"{n_steps}*{n_envs} = {n_steps * n_envs}{was} does not tile "
            f"the update geometry: {e}") from e


# module-level jit: a fresh `jax.jit(lambda ...)` per restore would defeat
# the jit cache and recompile the copy program on every rollback
# (jsan recompile-hazard, PR 3 first-run finding)
_fresh_copy_jit = jax.jit(lambda t: jax.tree.map(jnp.copy, t))


def _fresh_copy(tree: Any) -> Any:
    """Copy every restored array into a fresh device buffer. Orbax-restored
    buffers must NOT be donated back into a jitted step (donate_argnums):
    on the multi-device CPU backend that corrupts the heap (the seed's
    restore-then-run resume tests segfaulted the whole suite). One jitted
    copy decouples the training state from the restore machinery's
    buffers; sharding is preserved (copy is elementwise)."""
    return _fresh_copy_jit(tree)


def _state_tree(state: TrainState, key: jax.Array | None,
                extra: Any | None) -> dict:
    """TrainState holds non-serializable leaves (apply_fn, tx); persist only
    the array pytrees + step — the torch-state_dict analogue. ``extra`` is
    any additional array pytree (the Experiment checkpoints its rollout
    carry here so a resumed run replays the uninterrupted trajectory)."""
    tree: dict[str, Any] = {
        "step": state.step,
        "params": state.params,
        "opt_state": state.opt_state,
    }
    if key is not None:
        tree["key"] = key
    if extra is not None:
        tree["extra"] = extra
    return tree


class Checkpointer:
    """Rotating checkpoint store for one training run (or one PBT member).

    >>> ckpt = Checkpointer(dir, max_to_keep=3)
    >>> ckpt.save(step, train_state, key=rollout_key, meta={"lr": 3e-4})
    >>> state, key, meta = ckpt.restore(train_state, key)
    """

    def __init__(self, directory: str, max_to_keep: int | None = 3,
                 bus=None):
        # On XLA:CPU, Orbax's background save thread must not exist at
        # all: jax 0.4.x's CPU client is not thread-safe, and a second
        # thread touching jax while the main thread dispatches donated
        # train steps corrupts live device buffers (observed in CI as a
        # checkpoint labeled with a future step or int32 -1 poison, and
        # reproduced independently by the async engine's bisects — see
        # async_engine.py). Merely wait()ing after save() is NOT enough;
        # the thread's existence during the save window is the hazard.
        # Accelerator platforms keep async checkpointing: their client
        # is thread-safe and save latency actually matters there.
        self._mngr = ocp.CheckpointManager(
            directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True,
                enable_async_checkpointing=(
                    jax.default_backend() != "cpu")))
        self.last_restored_step: int | None = None
        # obs.EventBus (or None): save/restore/fallback/crc-reject events
        # land on the run's timeline so a post-mortem ties a rollback to
        # the exact step it restored and why the newer ones were rejected
        self._bus = bus

    def _emit(self, kind: str, **fields) -> None:
        if self._bus is not None:
            self._bus.emit(kind, **fields)

    @property
    def directory(self) -> str:
        return str(self._mngr.directory)

    def all_steps(self) -> list[int]:
        return sorted(self._mngr.all_steps())

    def latest_step(self) -> int | None:
        return self._mngr.latest_step()

    def save(self, step: int, state: TrainState,
             key: jax.Array | None = None, extra: Any | None = None,
             meta: dict | None = None, force: bool = False) -> bool:
        """Persist checkpoint ``step``. ``meta`` is a flat dict of JSON-able
        scalars (PBT stores hyperparams + fitness here); ``extra`` any array
        pytree. ``force=True`` overwrites an existing checkpoint at the same
        step (Orbax otherwise refuses the duplicate — needed when PBT
        exploit copies weights without a train step). Returns False when the
        save was skipped because the step already exists.

        A forced overwrite is delete-then-save (Orbax cannot swap a step in
        place), so it runs synchronously to keep the no-copy window as small
        as one save; a crash inside that window falls back to the previous
        retained step. Keep ``max_to_keep >= 2`` if you force-overwrite your
        only step."""
        if force:
            # an in-flight async save of the same step is invisible to
            # all_steps() until finalized — settle it first so force can't
            # silently degrade to a skipped save
            self.wait()
            if step in self._mngr.all_steps():
                # Orbax refuses duplicate steps outright (its ``force`` only
                # bypasses save-interval policy); overwrite = delete + save
                self._mngr.delete(step)
                try:
                    os.unlink(_sidecar_path(self.directory, step))
                except FileNotFoundError:
                    pass   # step predates the sidecar scheme
        try:
            saved = self._mngr.save(
                step,
                args=ocp.args.Composite(
                    state=ocp.args.StandardSave(_state_tree(state, key, extra)),
                    meta=ocp.args.JsonSave(dict(meta or {}))),
                force=force)
        except ocp.checkpoint_manager.StepAlreadyExistsError:
            return False
        if force:
            self.wait()
        self._emit("ckpt_save", step=step, force=force, saved=bool(saved))
        return bool(saved)

    def restore(self, template_state: TrainState,
                template_key: jax.Array | None = None,
                template_extra: Any | None = None,
                step: int | None = None, fallback: bool = True,
                ) -> tuple[TrainState, jax.Array | None, Any, dict]:
        """Restore into the shape/dtype/sharding of ``template_state`` (a
        live state from the same model/optimizer build — its values are
        ignored). Pass ``template_key``/``template_extra`` iff they were
        saved. Returns (state, key-or-None, extra-or-None, meta).

        Integrity fallback: restoring the LATEST step (``step=None``)
        verifies the step actually restores; a step whose files are
        truncated/corrupted (or a partial dir left by a crash inside the
        force-overwrite delete+save window) is skipped with a visible
        stderr warning and the previous retained step is restored instead.
        Only when EVERY retained step fails does this raise
        :class:`CheckpointRestoreError`. An explicit ``step`` (or
        ``fallback=False``) restores exactly that step and re-raises its
        failure. ``self.last_restored_step`` records which step won.

        The returned arrays live in fresh buffers (see :func:`_fresh_copy`)
        so callers may hand them straight to a donating jitted step."""
        template = _state_tree(template_state, template_key, template_extra)
        abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, template)
        restored = self._restore_candidates(
            step, fallback,
            lambda: ocp.args.Composite(
                state=ocp.args.StandardRestore(abstract),
                meta=ocp.args.JsonRestore()))
        tree = _fresh_copy(restored["state"])
        # TrainState is a flax struct (.replace); population MemberState is
        # a NamedTuple (._replace) — both checkpoint through the same path
        rep = getattr(template_state, "replace", None) or \
            template_state._replace
        state = rep(step=tree["step"], params=tree["params"],
                    opt_state=tree["opt_state"])
        return state, tree.get("key"), tree.get("extra"), dict(
            restored["meta"] or {})

    def _restore_candidates(self, step: int | None, fallback: bool,
                            build_args) -> Any:
        """The integrity-fallback candidate loop shared by
        :meth:`restore` and :meth:`elastic_restore`: newest retained step
        first, each pre-checked against its crc32 sidecar (a mismatch is
        rejected for the price of a re-read instead of a full failed
        deserialization), falling back on any failure until a step
        restores or every candidate is exhausted."""
        if step is not None:
            candidates = [step]
        else:
            candidates = sorted(self._mngr.all_steps(), reverse=True)
        if not candidates:
            raise FileNotFoundError(
                f"no checkpoint found under {self.directory}")
        errors: list[tuple[int, Exception]] = []
        for i, s in enumerate(candidates):
            try:
                self._verify_checksums(s)
                restored = self._mngr.restore(s, args=build_args())
                self.last_restored_step = s
                self._emit("ckpt_restore", step=s,
                           fallback_from=(candidates[0] if i else None),
                           rejected=len(errors))
                return restored
            except Exception as e:   # orbax surfaces corruption as
                errors.append((s, e))  # assorted exception types
                self._emit("ckpt_crc_reject"
                           if isinstance(e, CheckpointChecksumError)
                           else "ckpt_reject",
                           step=s, error=type(e).__name__,
                           detail=str(e)[:200])
                if step is not None or not fallback:
                    raise
                if i + 1 < len(candidates):
                    print(f"checkpoint: step {s} failed to restore "
                          f"({type(e).__name__}: {str(e)[:200]}); "
                          f"falling back to step {candidates[i + 1]}",
                          file=sys.stderr, flush=True)
        raise CheckpointRestoreError(
            f"all {len(candidates)} retained checkpoint steps under "
            f"{self.directory} failed to restore: "
            + "; ".join(f"step {s}: {type(e).__name__}"
                        for s, e in errors)) from errors[-1][1]

    def _verify_checksums(self, step: int) -> None:
        """Cheap integrity pre-check: compare checkpoint ``step``'s files
        against its crc32 sidecar. A step with no sidecar (crashed before
        ``wait()``, or pre-sidecar checkpoints) passes — the deep
        restore-failure fallback still covers it."""
        path = _sidecar_path(self.directory, step)
        try:
            with open(path) as f:
                expected = json.load(f)
        except FileNotFoundError:
            return
        for rel, crc in expected.items():
            full = os.path.join(self.directory, str(step), rel)
            try:
                actual = _crc32_file(full)
            except FileNotFoundError as e:
                raise CheckpointChecksumError(
                    f"checkpoint step {step}: payload file {rel} named in "
                    f"the checksum sidecar is missing") from e
            if actual != crc:
                raise CheckpointChecksumError(
                    f"checkpoint step {step}: crc32 mismatch on {rel} "
                    f"(sidecar {crc:#010x}, on disk {actual:#010x})")

    def elastic_restore(self, template_state: TrainState, *,
                        old_world: int, surviving_ranks,
                        old_n_envs: int | None = None, mesh=None,
                        geometry: tuple[int, int, int | None, int]
                        | None = None,
                        step: int | None = None, fallback: bool = True,
                        ) -> tuple[TrainState, jax.Array | None, Any, dict]:
        """Shrink-to-fit restore: load a checkpoint written when the data
        axis had ``old_world`` shards onto the smaller surviving topology.

        - ``params``/``opt_state``/``step`` are replicated state — world-
          size independent, restored unchanged (template-FREE restore:
          the saved shapes are authoritative, not a template built at
          either world size).
        - env-batched ``extra`` leaves keep only ``surviving_ranks``'
          contiguous row blocks. Which leaves are env-batched is decided
          by the partition-rule table
          (``parallel.sharding.ELASTIC_EXTRA_RULES``): leaves on the data
          axis with leading dim ``old_n_envs`` (inferred from the first
          extra leaf when not given) are sliced; rule-replicated leaves
          — PRNG keys, matched by NAME — pass through whole even when
          their length collides with ``old_n_envs``.
        - ``geometry`` = ``(n_epochs, n_minibatches, minibatch_size,
          n_steps)``, when given, is re-validated against the shrunk
          global batch via :func:`validate_shrunk_geometry` — the
          fail-fast on untileable shrink.
        - ``mesh``, when given, is the NEW (surviving) mesh: the state is
          placed replicated on it and the shrunk env batch is checked to
          divide its data axis. The extra tree is returned HOST-side
          (numpy): env-batched and non-batched leaves need different
          placements, which the caller owns (``dp.put_carry``).

        ``template_state`` supplies only the treedef/``replace``; its
        values and shardings are ignored. Same integrity fallback as
        :meth:`restore`. Returns ``(state, key, extra, meta)``."""
        import numpy as np

        surv = sorted(set(int(r) for r in surviving_ranks))
        restored = self._restore_candidates(
            step, fallback,
            lambda: ocp.args.Composite(
                state=ocp.args.StandardRestore(),
                meta=ocp.args.JsonRestore()))
        # host-side copies, not the jitted `_fresh_copy`: a template-free
        # restore brings leaves back under their SAVED shardings (old
        # mesh), which no single jit can consume alongside unsharded
        # leaves — and the old topology may not even exist anymore. The
        # numpy round-trip both decouples from orbax's buffers (the
        # donation hazard `_fresh_copy` exists for) and frees the state
        # from the dead world's layout; a restart path can afford it.
        tree = jax.tree.map(np.asarray, restored["state"])
        from rlgpuschedule_tpu.parallel import sharding as shardlib
        extra = tree.get("extra")
        new_n_envs = None
        leaves = jax.tree.leaves(extra) if extra is not None else []
        if leaves:
            if old_n_envs is None:
                old_n_envs = int(leaves[0].shape[0])
            if old_n_envs % old_world:
                raise ElasticRestoreError(
                    f"saved env batch {old_n_envs} does not tile the "
                    f"saved world's {old_world} data shards — cannot "
                    f"attribute rows to surviving ranks")
            new_n_envs = old_n_envs // old_world * len(surv)
            if geometry is not None:
                n_epochs, n_mb, mb_size, n_steps = geometry
                validate_shrunk_geometry(n_epochs, n_mb, mb_size, n_steps,
                                         new_n_envs, old_n_envs)
            extra = shardlib.shrink_env_rows_by_rule(
                extra, shardlib.ELASTIC_EXTRA_RULES,
                old_n_envs=old_n_envs, old_world=old_world,
                surviving_ranks=surv)
        rep = getattr(template_state, "replace", None) or \
            template_state._replace
        state = rep(step=tree["step"], params=tree["params"],
                    opt_state=tree["opt_state"])
        if mesh is not None:
            from rlgpuschedule_tpu.parallel.mesh import (DATA_AXIS,
                                                         replicated)
            n_data = mesh.shape[DATA_AXIS]
            if new_n_envs is not None and new_n_envs % n_data:
                raise ElasticRestoreError(
                    f"shrunk env batch {new_n_envs} not divisible by the "
                    f"surviving mesh's data axis ({n_data})")
            state = shardlib.put_global(state, replicated(mesh))
        self._emit("ckpt_elastic_restore", step=self.last_restored_step,
                   old_world=old_world, surviving_ranks=surv,
                   new_n_envs=new_n_envs)
        return state, tree.get("key"), extra, dict(restored["meta"] or {})

    def read_meta(self, step: int | None = None) -> dict:
        """Read a checkpoint's JSON meta without restoring its arrays
        (e.g. the best-checkpoint bar a resumed --keep-best run recovers)."""
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(
                f"no checkpoint found under {self.directory}")
        restored = self._mngr.restore(
            step, args=ocp.args.Composite(meta=ocp.args.JsonRestore()))
        return dict(restored["meta"] or {})

    def wait(self) -> None:
        """Block until async saves are durable (call before reading the
        files from another process, e.g. a PBT exploit copy), then settle
        the crc32 sidecars: write one for every retained step that lacks
        it (checksumming an in-flight save would record a torn view, so
        sidecars land here, not in ``save``) and prune sidecars whose
        step was rotated out."""
        self._mngr.wait_until_finished()
        steps = set(self._mngr.all_steps())
        for s in steps:
            if not os.path.exists(_sidecar_path(self.directory, s)):
                write_checksum_sidecar(self.directory, s)
        crc_dir = os.path.join(self.directory, ".crc")
        if os.path.isdir(crc_dir):
            for name in os.listdir(crc_dir):
                stem = name.partition(".")[0]
                if name.endswith(".json") and stem.isdigit() \
                        and int(stem) not in steps:
                    os.unlink(os.path.join(crc_dir, name))

    def close(self) -> None:
        self.wait()
        self._mngr.close()

    def __enter__(self) -> "Checkpointer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
